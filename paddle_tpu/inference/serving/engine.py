"""Continuous-batching serving engine over the paged KV cache.

The serving tier the ROADMAP's "heavy traffic" north star asks for:
iteration-level scheduling (Orca) + a paged KV cache (PagedAttention) on
top of the compiled decode path PR 2 built (donated buffers, one program
per shape).

Design (docs/SERVING.md):

* **One compiled decode program.** The decode step runs over a FIXED
  ``max_slots``-wide slot table — shapes never change, so it traces once
  and the per-iteration host cost is one dispatch. The iteration bound is
  a DEVICE SCALAR argument (no retrace): with work queued the dispatch
  returns exactly when the first live slot exhausts its budget, so
  retirement/admission happen with zero idle iterations; with the queue
  empty one dispatch drains the whole tail. ``decode_chunk`` caps the
  bound only when a live slot can retire EARLY (EOS enabled), a prompt is
  mid-chunked-prefill, or the caller streams (token granularity).
* **On-demand paged KV + preemption.** A sequence holds only the blocks
  covering KV it has actually written: admission allocates the prompt's
  blocks (prefix-cache hits are MAPPED, not recomputed), decode extends
  block by block ahead of each dispatch. When the pool runs dry the
  newest-admitted running sequence is PREEMPTED — blocks freed, tokens
  kept, re-queued at the front for recompute-on-readmission (greedy
  recompute is bit-identical) — so worst-case ``max_new`` budgets are
  never pre-charged and effective concurrency tracks real usage.
  ``preempt=False`` restores the legacy reservation-at-admission mode.
* **Automatic prefix caching.** Full KV blocks are content-hashed (chained
  block-aligned token-id keys) into the ref-counted ``BlockManager`` table
  as prefill/decode completes them; admissions sharing a system-prompt /
  few-shot prefix map the cached blocks and prefill only their suffix.
  Refcount-0 blocks stay cached on an LRU list until allocation pressure
  evicts them. ``prefix_cache=False`` disables.
* **Chunked prefill.** Prompts longer than ``prefill_chunk`` prefill in
  fixed-size chunks (``models.generation.paged_prefill_chunk`` — offset
  and length are device scalars) interleaved with decode dispatches, so a
  long admission no longer freezes in-flight streams. Short cold prompts
  still take the BATCHED bucketed prefill: one dispatch per power-of-2
  length bucket with the batch dim padded to the power-of-2 bucket of the
  admission-wave size.
* **Overload-safe lifecycle + policy scheduling.** Every request ends in
  exactly one terminal state (``finished`` / ``cancelled`` /
  ``timed_out`` / ``shed``): ``cancel(rid)`` and per-request
  ``timeout_s``/``deadline_s`` free KV blocks mid-flight through the
  preemption path (free, do-not-requeue), checked every ``step()``;
  admission order is a pluggable ``AdmissionPolicy`` (FIFO default,
  priority / weighted fair share per ``tenant`` / earliest-deadline-
  first), the bounded queue SHEDS with a retry-after hint instead of
  blocking, and ``health_snapshot()`` + the global hang watchdog
  (``serving.step``/``serving.prefill``/``serving.decode`` sections)
  expose the whole thing to ops endpoints.
* **On-device sampling.** Per-request temperature / top-k / top-p ride
  the compiled decode step as DEVICE OPERANDS in the slot table (one
  compile serves every request mix — no per-request executables), with
  per-request PRNG base keys derived from ``seed``: the token at sample
  index ``t`` is drawn with ``fold_in(seed_key(seed), t)``, so sampled
  streams are reproducible per ``(request, seed)`` across
  preemption-recompute, supervisor crash-resubmit and cross-replica
  failover. ``temperature=0`` (the default) selects the argmax through a
  ``jnp.where`` and stays BIT-IDENTICAL to the v1 greedy engine — every
  greedy parity oracle extends unchanged. int8 weight-only decode rides
  transparently via ``quantize="int8"``.
* **Speculative decoding.** ``spec_decode=k`` drafts up to ``k`` tokens
  per step by n-gram prompt lookup (no second model: the draft is the
  continuation of the last ``spec_ngram`` tokens' most recent earlier
  occurrence in the request's own context) and VERIFIES them in one
  multi-query decode dispatch (``models.generation.paged_spec_step``;
  the PR 10 paged-attention kernel's second entry point). Accepted
  tokens commit their KV blocks; the rejected tail's surplus blocks
  free through the same ref-counted paths preemption exercises. Because
  sampling keys are a pure function of the token index, speculative
  output is BIT-IDENTICAL to non-speculative decode at every
  temperature — acceptance only changes speed, never tokens. Steps with
  no draftable slot fall through to the plain decode dispatch, so
  incoherent (low-acceptance) traffic pays no verify overhead.

API::

    engine = ServingEngine(params, model_cfg, ServingConfig(max_slots=8))
    rid = engine.submit(prompt_ids, max_new_tokens=64)
    while engine.pending:
        for rid, toks in engine.step().items(): ...
    # or: for rid, tok in engine.stream(): ...
    # or: outs = engine.run(prompts, max_new_tokens=64)
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ...flags import flag
from ...health import watchdog as _watchdog
from .offload import block_crc as _block_crc
from .paged_cache import PagedKVCache
from .policies import resolve_policy
from .scheduler import (CANCELLED, DEFAULT_TENANT, SHED,  # noqa: F401
                        TIMED_OUT, Request, Scheduler, ServingQueueFull)

__all__ = ["AdoptError", "ServingConfig", "ServingEngine", "EnginePrograms",
           "HEALTH_SNAPSHOT_FIELDS", "SUPERVISOR_SNAPSHOT_KEYS"]

_UNSET = "unset"

# field -> meaning for health_snapshot(); docs/OPS.md's generated table
# (ops.gen_docs) renders this, and the snapshot test pins the live
# payload's keys to it, so the doc cannot drift from the code. The engine
# serves every field except SUPERVISOR_SNAPSHOT_KEYS, which the
# EngineSupervisor layers on top (supervisor.health_snapshot()).
HEALTH_SNAPSHOT_FIELDS = {
    "ok": "False only when the installed hang watchdog has fired "
          "(shedding is a healthy degraded mode, not unhealth)",
    "accepting": "whether a submit() right now would QUEUE rather than "
                 "shed (queue below its bound; under a supervisor also "
                 "requires not-draining and restart budget remaining)",
    "policy": "active admission policy name (fifo/priority/fair/edf)",
    "queued": "requests waiting for a slot",
    "queue_limit": "admission-queue bound; submits past it shed with "
                   "ServingQueueFull",
    "live_slots": "occupied decode slots",
    "max_slots": "slot-table width (the compiled decode batch dim)",
    "free_blocks": "KV blocks allocatable right now (free list + "
                   "evictable refcount-0 cached blocks)",
    "usable_blocks": "pool size excluding the reserved null block — the "
                     "EFFECTIVE capacity: at a fixed byte budget an int8 "
                     "pool holds ~2-4x the blocks of an fp one",
    "kv_pool_bytes": "device bytes the KV pool holds GLOBALLY (K + V + "
                     "the scale planes on quantized layouts, summed over "
                     "every tp shard) — the denominator of the int8 "
                     "capacity win",
    "tp_degree": "tensor-parallel degree of this replica "
                 "(ServingConfig.tp / FLAGS_serving_tp): the paged pool "
                 "is sharded over this many devices on its kv-heads axis; "
                 "1 = the single-device engine",
    "kv_pool_shard_bytes": "KV-pool bytes ONE device holds "
                           "(kv_pool_bytes / tp_degree — the kv-heads "
                           "split is exact): what a per-chip HBM budget "
                           "must cover, so the autoscaler and capacity "
                           "planning see sharded replicas correctly",
    "kv_quant": "KV-pool quantization mode (null = fp at the model/cache "
                "dtype; 'int8' = int8 blocks + per-token-per-head fp32 "
                "scales, dequant fused into the kernel's loads)",
    "paged_kernel": "decode attention path: true = the Pallas "
                    "flash-decoding paged-attention kernel (block tables "
                    "consumed in-kernel), false = the XLA gather + masked-"
                    "softmax fallback (FLAGS_serving_paged_kernel)",
    "spec_decode": "speculative-decoding draft width: tokens drafted per "
                   "verify dispatch via n-gram prompt lookup "
                   "(FLAGS_serving_spec_decode; 0 = off). Acceptance "
                   "counters ride stats() as spec_drafted / spec_accepted "
                   "— output streams are bit-identical to non-speculative "
                   "decode, so the knob only moves tokens/s",
    "retry_after_s": "suggested client backoff when shedding: the mean "
                     "recent retirement interval (the conservative "
                     "FLAGS_serving_retry_after_s default before two "
                     "retirements exist to estimate from)",
    "counters": "lifetime totals: admitted / retired / cancelled / "
                "timed_out / shed / preemptions / oom_truncated / "
                "prefix_hit_tokens / evictions",
    "dispatch_latency": "per-kind device-dispatch wall time (ISSUE 20): "
                        "for each of prefill / decode / mixed / spec, the "
                        "lifetime dispatch count plus p50_ms / p99_ms over "
                        "a recent window (null until that kind has "
                        "dispatched) — the prefill-stall this splits out "
                        "is exactly what mixed batching removes, so "
                        "operators can watch it",
    "offload": "host-RAM KV offload tier (FLAGS_serving_offload; ISSUE "
               "16): enabled + the tier's capacity / blocks (host-"
               "resident now) / swap_outs / swap_ins / tier_hits / "
               "tier_misses / corrupt_drops (checksum or token-mismatch "
               "entries dropped — degraded to a MISS, never attended) / "
               "tier_evictions; all zeros with the tier off",
    "lora": "multi-adapter LoRA serving (ISSUE 19): enabled + rank / "
            "slots (device adapter-pool rows past the reserved zeroed "
            "base slot 0) / resident (adapter names loaded on device) / "
            "adapters_registered / adapters_resident / adapter_loads "
            "(H2D uploads — cold acquires) / adapter_evictions (LRU "
            "slot reclaims) / adapter_pins (running-request pins; a "
            "pinned adapter is never evicted mid-stream); zeros with "
            "multi-adapter serving off",
    "watchdog": "global hang-watchdog state: installed / fired / "
                "timeout_s",
    "tenants": "per-tenant breakdown: queued / live / submitted / "
               "admitted / retired / cancelled / timed_out / shed / "
               "service_tokens / cached_blocks / ttft_p50_s / ttft_p99_s "
               "/ tpot_p50_s / tpot_p99_s (TPOT = mean inter-token decode "
               "latency per request; percentiles over recent requests)",
    "supervisor": "EngineSupervisor layer (supervisor snapshots only): "
                  "restarts / restart_budget / broken / draining / "
                  "accepting / resubmitted / recovered_tokens / adopted "
                  "(requests failed over FROM another replica) / "
                  "migrated_in + migrated_out (live KV migrations adopted "
                  "here / released here; ISSUE 16) / completed / crashes "
                  "(most recent restart reasons)",
    "autoscale": "autoscale_signal() record (supervisor snapshots only): "
                 "action (scale_up/scale_in/hold) + reason + "
                 "queue_pressure / utilization / shed_delta — the "
                 "telemetry an autoscaler consumes, writable as the "
                 "launcher's --elastic_rejoin_file format",
}

# snapshot fields only the EngineSupervisor adds; the engine-level payload
# is HEALTH_SNAPSHOT_FIELDS minus these (the shape test pins both layers)
SUPERVISOR_SNAPSHOT_KEYS = ("supervisor", "autoscale")


class AdoptError(RuntimeError):
    """A migration target refused a serialized request (pool full, no free
    slot, KV-layout/TP-shape mismatch, over-long chain). The caller falls
    back to the resubmit path — recompute instead of transfer, outputs
    still bit-identical."""


@dataclasses.dataclass
class EnginePrograms:
    """The compiled prefill/chunk/decode executables plus the stats dict
    and bucket set their trace-counter closures mutate. Shareable across
    engine rebuilds with an IDENTICAL shape signature — the supervisor's
    restart path hands the dead engine's programs to its replacement, so
    crash recovery never recompiles (and the shared trace counters PROVE
    it: decode_traces must not grow across a restart)."""

    prefill: Any
    chunk: Any
    decode: Any
    spec: Any           # speculative verify (multi-query decode) program
    sample: Any         # first-token sampler (prefill-logits -> token)
    stats: Dict[str, int]
    prefill_buckets: set
    key: tuple          # shape signature (incl. the sampling/spec-decode
    #                     surface: spec_decode widths change the verify
    #                     program's shapes, and the LoRA pool geometry /
    #                     embed-model config change operand shapes); reuse
    #                     under a different one raises
    embed: Any = None   # prefill-only embeddings encoder (ISSUE 19);
    #                     None when no embed model is attached
    mixed: Any = None   # mixed prefill+decode step (ISSUE 20): per-row
    #                     start/q_len device operands, so one executable
    #                     per Q bucket serves every role mix. Built with
    #                     the others regardless of ServingConfig.
    #                     mixed_batch (the flag gates DISPATCH, not
    #                     shapes), so engines on either side of the flag
    #                     share one program set


@dataclasses.dataclass
class ServingConfig:
    """Engine shape/capacity knobs. ``None`` fields resolve from the
    ``FLAGS_serving_*`` registry at construction (flags.py), so a fleet can
    retune the engine from the environment without code changes.

    The three feature knobs use the ``"unset"`` sentinel instead (the same
    convention as ``GenerationConfig.resolve``): left unset they resolve
    from their flag; an EXPLICIT ``None`` (or ``False``/``0``) disables
    the feature even when the flag enables it — ``prefix_cache=None`` and
    ``prefill_chunk=None`` are real overrides, not "not given".
    """

    block_size: Optional[int] = None
    max_slots: Optional[int] = None
    max_model_len: Optional[int] = None
    queue_depth: Optional[int] = None
    decode_chunk: Optional[int] = None
    tp: Optional[int] = None         # tensor-parallel degree (ISSUE 12):
    #                                  the paged pool shards its kv-heads
    #                                  axis over a "tp" mesh of this many
    #                                  devices and the compiled programs
    #                                  run under shard_map; None ->
    #                                  FLAGS_serving_tp (default 1 = the
    #                                  single-device engine, byte-for-byte
    #                                  today's code path). Requires
    #                                  num_kv_heads % tp == 0 (validated
    #                                  with a structured error).
    num_blocks: int = 0              # 0 = auto (max_slots full sequences)
    quantize: Optional[str] = None   # "int8" -> weight-only decode path
    cache_dtype: Any = None          # None -> model activation dtype
    kv_quant: Any = _UNSET           # "int8" -> quantized KV pool (int8
    #                                  blocks + per-token-per-head scales);
    #                                  unset -> FLAGS_serving_kv_quant;
    #                                  None/"" = fp pool. Composes with
    #                                  quantize="int8" (weights).
    paged_kernel: Any = _UNSET       # decode attention path: True/"on" =
    #                                  Pallas flash-decoding kernel
    #                                  (interpret off-TPU), False/"off" =
    #                                  XLA gather fallback, "auto" = kernel
    #                                  on TPU only; unset ->
    #                                  FLAGS_serving_paged_kernel
    prefix_cache: Any = _UNSET       # bool; None/False = off
    prefill_chunk: Any = _UNSET      # tokens/chunk; None/0 = whole prompt
    preempt: Any = _UNSET            # bool; None/False = legacy reservation
    mixed_batch: Any = _UNSET        # bool (ISSUE 20): mid-flight prefill
    #                                  chunks ride the decode dispatch as
    #                                  extra query rows of ONE mixed step;
    #                                  None/False = the two-phase path
    #                                  (chunk dispatches before a clamped
    #                                  decode dispatch — the parity
    #                                  oracle); unset ->
    #                                  FLAGS_serving_mixed_batch
    # speculative decoding (ISSUE 11)
    spec_decode: Any = _UNSET        # draft tokens per verify dispatch
    #                                  (n-gram prompt lookup); None/0 =
    #                                  off; unset -> FLAGS_serving_
    #                                  spec_decode
    spec_ngram: Any = _UNSET         # n-gram length the drafter matches;
    #                                  unset/None -> FLAGS_serving_
    #                                  spec_ngram
    # overload / multi-tenancy (ISSUE 6)
    policy: Any = None               # AdmissionPolicy | "fifo"/"priority"/
    #                                  "fair"/"edf"; None -> FLAGS_serving_
    #                                  policy (default fifo)
    tenant_cache_quota: Any = _UNSET  # max prefix-cache blocks one tenant
    #                                   may keep registered; None/0 = off
    # host-RAM KV offload tier (ISSUE 16)
    offload: Any = _UNSET            # bool; evicted registered blocks swap
    #                                  to a bounded host pool instead of
    #                                  dying; unset -> FLAGS_serving_offload
    offload_blocks: Any = _UNSET     # host-tier capacity bound in blocks;
    #                                  unset -> FLAGS_serving_offload_blocks
    # multi-adapter LoRA serving (ISSUE 19)
    lora_rank: Optional[int] = None  # adapter rank r (fixed pool-wide);
    #                                  None -> FLAGS_serving_lora_rank
    lora_slots: Optional[int] = None  # device adapter-pool slots (on top
    #                                   of the reserved zeroed base slot
    #                                   0); 0 disables multi-adapter
    #                                   serving entirely — the compiled
    #                                   programs are then byte-identical
    #                                   to the LoRA-less engine; None ->
    #                                   FLAGS_serving_lora_slots
    lora_pool: Optional[int] = None  # host-registry capacity (adapters
    #                                  registered in total, >= lora_slots);
    #                                  None -> FLAGS_serving_lora_pool

    def __post_init__(self):
        for f, name in (("block_size", "FLAGS_serving_block_size"),
                        ("max_slots", "FLAGS_serving_max_slots"),
                        ("max_model_len", "FLAGS_serving_max_model_len"),
                        ("queue_depth", "FLAGS_serving_queue_depth"),
                        ("decode_chunk", "FLAGS_serving_decode_chunk"),
                        ("tp", "FLAGS_serving_tp"),
                        ("lora_rank", "FLAGS_serving_lora_rank"),
                        ("lora_slots", "FLAGS_serving_lora_slots"),
                        ("lora_pool", "FLAGS_serving_lora_pool")):
            if getattr(self, f) is None:
                setattr(self, f, int(flag(name)))
        self.lora_rank = int(self.lora_rank)
        self.lora_slots = int(self.lora_slots)
        self.lora_pool = int(self.lora_pool)
        if self.lora_slots < 0:
            raise ValueError(f"lora_slots must be >= 0 (0 = multi-adapter "
                             f"serving off), got {self.lora_slots}")
        if self.lora_slots and self.lora_pool < self.lora_slots:
            raise ValueError(
                f"lora_pool ({self.lora_pool}) must be >= lora_slots "
                f"({self.lora_slots}): the host registry backs every "
                f"device-resident adapter (FLAGS_serving_lora_pool / "
                f"FLAGS_serving_lora_slots)")
        self.tp = int(self.tp)
        if self.tp < 1:
            raise ValueError(f"tensor-parallel degree must be >= 1 (1 = "
                             f"the single-device engine), got tp={self.tp}")
        if self.prefix_cache == _UNSET:
            self.prefix_cache = bool(flag("FLAGS_serving_prefix_cache"))
        else:
            self.prefix_cache = bool(self.prefix_cache)
        if self.preempt == _UNSET:
            self.preempt = bool(flag("FLAGS_serving_preempt"))
        else:
            self.preempt = bool(self.preempt)
        if self.mixed_batch == _UNSET:
            self.mixed_batch = bool(flag("FLAGS_serving_mixed_batch"))
        else:
            self.mixed_batch = bool(self.mixed_batch)
        if self.prefill_chunk == _UNSET:
            self.prefill_chunk = int(flag("FLAGS_serving_prefill_chunk"))
        self.prefill_chunk = (int(self.prefill_chunk)
                              if self.prefill_chunk else None)
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1 or None/0 "
                             f"(got {self.prefill_chunk})")
        if self.spec_decode == _UNSET:
            self.spec_decode = int(flag("FLAGS_serving_spec_decode"))
        self.spec_decode = int(self.spec_decode) if self.spec_decode else 0
        if self.spec_decode < 0:
            raise ValueError(f"spec_decode must be >= 0 (draft tokens per "
                             f"verify; 0 = off), got {self.spec_decode}")
        if self.spec_ngram in (_UNSET, None):
            self.spec_ngram = int(flag("FLAGS_serving_spec_ngram"))
        self.spec_ngram = int(self.spec_ngram)
        if self.spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, "
                             f"got {self.spec_ngram}")
        if self.tenant_cache_quota == _UNSET:
            self.tenant_cache_quota = int(
                flag("FLAGS_serving_tenant_cache_quota"))
        self.tenant_cache_quota = (int(self.tenant_cache_quota)
                                   if self.tenant_cache_quota else None)
        if self.offload == _UNSET:
            self.offload = bool(flag("FLAGS_serving_offload"))
        else:
            self.offload = bool(self.offload)
        if self.offload_blocks == _UNSET:
            self.offload_blocks = int(flag("FLAGS_serving_offload_blocks"))
        self.offload_blocks = (int(self.offload_blocks)
                               if self.offload_blocks else 0)
        if self.policy is None:
            self.policy = str(flag("FLAGS_serving_policy"))
        from ...models.llama import (KV_QUANT_MODES, QUANTIZE_MODES,
                                     validate_quant_mode)
        validate_quant_mode(self.quantize, QUANTIZE_MODES)
        if self.kv_quant == _UNSET:
            self.kv_quant = str(flag("FLAGS_serving_kv_quant"))
        self.kv_quant = self.kv_quant or None      # ""/False -> fp pool
        validate_quant_mode(self.kv_quant, KV_QUANT_MODES, "kv_quant")
        if self.paged_kernel == _UNSET:
            self.paged_kernel = str(flag("FLAGS_serving_paged_kernel"))
        from ...kernels.dispatch import use_pallas
        # resolve once at construction (structured error on bad knobs);
        # the resolved bool keys the compiled-program signature
        self.paged_kernel = use_pallas(self.paged_kernel)


class ServingEngine:
    """Continuous-batching greedy decode service over a causal-LM pytree."""

    # chaos hook (testing/chaos.py ``stale_directory``): when set, the
    # NEXT export_chain() flips one byte in its payload AFTER stamping
    # the checksums, so the receiving graft_chain() must detect the
    # mismatch and degrade to recompute — the fleet-cache pull's
    # corruption drill. Class-level default; injectors set it per
    # instance and the export consumes it.
    _corrupt_next_export = False

    def __init__(self, params, model_config, serving_config:
                 Optional[ServingConfig] = None, gen_config=None,
                 programs: Optional[EnginePrograms] = None,
                 journal=None, embed_model=None):
        import jax

        from ...models.generation import GenerationConfig, validate_sampling
        self.config = serving_config or ServingConfig()
        # durable serving (ISSUE 18): a RequestJournal (possibly shared
        # fleet-wide) that this engine feeds under its own lock — submit
        # records, per-step delivered-token cursors, terminal
        # transitions — with ONE flush (fsync under the 'step' policy)
        # per step. None = durability off, zero overhead.
        self.journal = journal
        self._jlive: Dict[int, int] = {}   # rid -> owned journal jid
        self._gen = gen_config or GenerationConfig()
        # the engine-default sampling knobs must themselves be servable
        # (per-request overrides are validated again at submit)
        validate_sampling(self._gen)
        from ...models.llama import ensure_quantized
        self._params = ensure_quantized(params, self.config.quantize)
        self._cfg = model_config
        # tensor parallelism (ISSUE 12): tp > 1 builds the "tp" mesh over
        # the replica's device slice, lays the QKV projections out
        # column-sharded (everything else replicated — the ONE
        # shard_serving_params layout) and emits the paged pool sharded on
        # its kv-heads axis. The scheduler / BlockManager / prefix cache
        # below stay device-count-agnostic: block ids are global, tables
        # and slot operands replicate, only pool bytes split — per-chip KV
        # capacity multiplies by tp at unchanged block-table logic.
        if self.config.tp > 1:
            from ...distributed.topology import tp_mesh
            from ...models.generation import validate_tp
            from ...models.llama import shard_serving_params
            validate_tp(model_config, self.config.tp)
            self._mesh = tp_mesh(self.config.tp)
            self._params = shard_serving_params(self._params, self._mesh)
        else:
            self._mesh = None
        self.cache = PagedKVCache(model_config, self.config.max_slots,
                                  self.config.max_model_len,
                                  self.config.block_size,
                                  self.config.num_blocks,
                                  dtype=self.config.cache_dtype,
                                  prefix_cache=self.config.prefix_cache,
                                  tenant_quota=self.config.tenant_cache_quota,
                                  kv_quant=self.config.kv_quant,
                                  mesh=self._mesh,
                                  offload=self.config.offload,
                                  offload_blocks=self.config.offload_blocks)
        self._policy = resolve_policy(
            self.config.policy,
            ttft_slo_s=float(flag("FLAGS_serving_ttft_slo_s")))
        self._sched = Scheduler(self.cache, self.config.max_slots,
                                self.config.queue_depth,
                                preempt=self.config.preempt,
                                policy=self._policy)
        M = self.config.max_slots
        self._tokens = np.zeros((M,), np.int32)
        self._seq_lens = np.zeros((M,), np.int32)
        self._steps_left = np.zeros((M,), np.int32)
        self._done = np.ones((M,), bool)          # empty slots are inactive
        self._eos = np.full((M,), -1, np.int32)
        # per-slot sampling operands (ISSUE 11): device operands of the
        # ONE compiled decode program, so a greedy request and a
        # temperature/top-k/top-p request share an executable. keys hold
        # each request's PRNG base key; sample_idx the next token index
        # (the fold_in operand — reproducibility per (request, seed))
        self._temp = np.zeros((M,), np.float32)
        self._topk = np.zeros((M,), np.int32)     # 0 = disabled
        self._topp = np.ones((M,), np.float32)    # 1.0 = disabled
        self._keys = np.zeros((M, 2), np.uint32)
        self._sample_idx = np.zeros((M,), np.int32)
        # multi-adapter LoRA (ISSUE 19): the device adapter pool plus the
        # per-slot adapter-row operand of every dispatch (0 = the zeroed
        # base adapter) and the rid -> adapter pin map the admission gate
        # maintains (pins persist across preemption; released only at a
        # terminal state, so an in-flight stream's weights never swap out)
        if self.config.lora_slots:
            from ...models.lora import AdapterPool
            self._lora = AdapterPool(model_config, self.config.lora_rank,
                                     self.config.lora_slots,
                                     self.config.lora_pool,
                                     mesh=self._mesh)
        else:
            self._lora = None
        self._adapters = np.zeros((M,), np.int32)
        self._lora_pinned: Dict[int, str] = {}
        # embeddings endpoint (ISSUE 19): an optional (BertConfig, params)
        # encoder serving prefill-only requests (kind "embed") — proof the
        # engine is model-agnostic beyond llama. Replicated even under TP
        # (a BERT-base forward is tiny next to the LM's KV traffic).
        if embed_model is not None:
            self._embed_cfg, self._embed_params = embed_model
        else:
            self._embed_cfg = self._embed_params = None
        # speculative decoding (ISSUE 11)
        self._spec_k = int(self.config.spec_decode)
        self._spec_n = int(self.config.spec_ngram)
        # every mutation (submit/cancel/step) and every snapshot read runs
        # under this lock, so stats()/health_snapshot() are safe from ANY
        # thread — the metrics endpoint polls while the engine thread
        # serves, and a mid-step torn read (counters from one dispatch,
        # slot table from the next) must be impossible. Reentrant: the
        # stream() GeneratorExit path cancels while a step frame may still
        # hold the lock on the same thread.
        self._lock = threading.RLock()
        # widest token buffer one dispatch can emit per slot (a budget
        # never exceeds max_model_len KV entries, so neither can steps)
        self._out_width = int(self.config.max_model_len)
        self._jax = jax
        # tp (the mesh shape) is part of the signature: engines at
        # different mesh shapes never share programs; same shape shares —
        # a supervisor rebuild or router spawn of a TP replica reuses the
        # dead engine's executables without retracing (flat decode_traces)
        key = (model_config, self.config.block_size, self.config.max_slots,
               self.config.max_model_len, self.config.quantize,
               str(self.config.cache_dtype), self.config.kv_quant,
               self.config.paged_kernel, self.config.spec_decode,
               self.config.tp,
               # LoRA pool geometry changes the gathered-matmul operand
               # shapes (rank normalized to 0 when disabled so base
               # engines share programs regardless of the rank flag);
               # the embed config keys the encoder program's shapes
               self.config.lora_rank if self.config.lora_slots else 0,
               self.config.lora_slots, self._embed_cfg)
        if programs is not None:
            if programs.key != key:
                raise ValueError(
                    "EnginePrograms were compiled for a different engine "
                    "shape; rebuild with programs=None")
            # SHARED stats/buckets: trace counters keep accumulating in
            # one place across rebuilds, proving recovery never retraces
            self._stats = programs.stats
            self._prefill_buckets = programs.prefill_buckets
            self._jprefill, self._jchunk, self._jdecode = (
                programs.prefill, programs.chunk, programs.decode)
            self._jspec, self._jsample = programs.spec, programs.sample
            self._jembed = programs.embed
            self._jmixed = programs.mixed
            self.programs = programs
        else:
            self._stats = {"decode_traces": 0, "prefill_traces": 0,
                           "chunk_prefill_traces": 0, "chunks": 0,
                           "steps": 0, "spec_traces": 0,
                           "sample_traces": 0, "spec_steps": 0,
                           "embed_traces": 0, "embeds": 0,
                           "mixed_traces": 0, "prefill_dispatches": 0,
                           "decode_dispatches": 0, "mixed_dispatches": 0,
                           "spec_dispatches": 0}
            self._prefill_buckets = set()
            (self._jprefill, self._jchunk, self._jdecode, self._jspec,
             self._jsample, self._jmixed) = self._build(jax)
            self._jembed = (self._build_embed(jax)
                            if self._embed_params is not None else None)
            self.programs = EnginePrograms(
                self._jprefill, self._jchunk, self._jdecode, self._jspec,
                self._jsample, self._stats, self._prefill_buckets, key,
                embed=self._jembed, mixed=self._jmixed)
        # per-dispatch wall-time observability (ISSUE 20): bounded recent
        # windows per dispatch KIND, feeding the p50/p99 rows stats() and
        # health_snapshot() expose. Per-engine (not shared with the
        # programs): latency is a property of THIS replica's host+device,
        # not of the executables
        self._dispatch_ms = {k: collections.deque(maxlen=512)
                             for k in ("prefill", "decode", "mixed",
                                       "spec")}

    # ---- compiled programs ------------------------------------------------

    def _build(self, jax):
        import jax.numpy as jnp
        from jax import lax

        from ...jit.train_step import donation_supported
        from ...models import generation as G
        cfg, stats, Cmax = self._cfg, self._stats, self._out_width
        if self._mesh is not None:
            # the LOCAL config the shard_map'd programs close over: head
            # counts stay global (the paged entry points derive the local
            # slice from the pool shard's shape); tp_axis names the mesh
            # axis the attention-output merge all_gathers over
            cfg = dataclasses.replace(cfg, tp_axis="tp")

        # every program takes the LoRA operand LAST ({"ids": per-row
        # adapter slots, "layers": the stacked pool} — a device operand
        # like the sampling knobs, so adapter churn never retraces); with
        # multi-adapter serving off it is bound to None below and the
        # traced computation is byte-identical to the LoRA-less engine
        def prefill_fn(params, ids, prompt_lens, block_tables, pool, active,
                       lora):
            stats["prefill_traces"] += 1           # trace-time only
            return G.paged_prefill(params, cfg, ids, prompt_lens,
                                   block_tables, pool, active, lora=lora)

        def chunk_fn(params, ids, start, chunk_len, block_tables, pool,
                     lora):
            stats["chunk_prefill_traces"] += 1     # trace-time only
            return G.paged_prefill_chunk(params, cfg, ids, start, chunk_len,
                                         block_tables, pool, lora=lora)

        use_kernel = self.config.paged_kernel

        def _next_tokens(logits, keys, sample_idx, temp, topk, topp):
            """One compiled sampling step over per-slot DEVICE operands:
            per-row keys fold the slot's base key with its sample index,
            then greedy rows take the argmax bitwise (sample_tokens'
            where-select) — gated behind a runtime cond so an all-greedy
            dispatch never pays the sampling sort."""
            kt = jax.vmap(jax.random.fold_in)(keys, sample_idx)
            return lax.cond(
                (temp > 0.0).any(),
                lambda lg: G.sample_tokens(lg, kt, temp, topk, topp),
                lambda lg: jnp.argmax(lg, axis=-1).astype(jnp.int32),
                logits)

        def decode_fn(params, pool, tokens, seq_lens, steps_left, done,
                      block_tables, eos_ids, limit, keys, sample_idx,
                      temp, topk, topp, lora):
            stats["decode_traces"] += 1            # trace-time only
            M = tokens.shape[0]

            # while (not scan): the chunk EXITS the moment every live row
            # is done, so a retirement wave mid-chunk costs nothing — the
            # same alive-mask early exit the batch generate() loop uses.
            # ``limit`` is a device scalar, so the host can size every
            # dispatch to the schedule (return at the next budget
            # retirement; drain the tail in one go) without retracing
            def body(carry):
                i, tokens, seq_lens, steps_left, done, sample_idx, pool, \
                    out = carry
                active = (~done) & (steps_left > 0)
                logits, pool, _drops = G.paged_decode_step(
                    params, cfg, tokens, seq_lens, block_tables, pool,
                    active, use_kernel=use_kernel, lora=lora)
                nxt = _next_tokens(logits, keys, sample_idx, temp, topk,
                                   topp)
                nxt = jnp.where(active, nxt, tokens)
                done = done | (active & (nxt == eos_ids))
                seq_lens = seq_lens + active
                sample_idx = sample_idx + active
                steps_left = steps_left - active.astype(jnp.int32)
                out = lax.dynamic_update_slice(out, nxt[:, None], (0, i))
                return (i + 1, nxt, seq_lens, steps_left, done, sample_idx,
                        pool, out)

            def cond(carry):
                i, _, _, steps_left, done, _, _, _ = carry
                return (i < limit) & ((~done) & (steps_left > 0)).any()

            out0 = jnp.zeros((M, Cmax), jnp.int32)
            (_, tokens, seq_lens, steps_left, done, _, pool, out) = \
                lax.while_loop(cond, body, (jnp.int32(0), tokens, seq_lens,
                                            steps_left, done, sample_idx,
                                            pool, out0))
            return pool, tokens, seq_lens, steps_left, done, out

        def spec_fn(params, pool, tokens, seq_lens, draft_lens, steps_left,
                    done, block_tables, keys, sample_idx, temp, topk, topp,
                    lora):
            """One speculative VERIFY dispatch: multi-query decode over
            ``tokens [M, Q]`` (last token + drafts), then sample each
            position with its own per-index key and count the accepted
            draft prefix. Tokens match non-speculative decode bitwise —
            index ``t`` is always drawn with ``fold_in(base, t)``."""
            stats["spec_traces"] += 1              # trace-time only
            M, Q = tokens.shape
            active = (~done) & (steps_left > 0)
            logits, pool, _drops = G.paged_spec_step(
                params, cfg, tokens, seq_lens, draft_lens, block_tables,
                pool, active, use_kernel=use_kernel, lora=lora)
            V = logits.shape[-1]
            idx = sample_idx[:, None] + jnp.arange(Q)[None, :]   # [M, Q]
            kt = jax.vmap(jax.vmap(jax.random.fold_in,
                                   in_axes=(None, 0)))(keys, idx)

            def _sampled(lg):
                return G.sample_tokens(
                    lg.reshape(M * Q, V), kt.reshape(M * Q, 2),
                    jnp.repeat(temp, Q), jnp.repeat(topk, Q),
                    jnp.repeat(topp, Q)).reshape(M, Q)

            cand = lax.cond(
                (temp > 0.0).any(), _sampled,
                lambda lg: jnp.argmax(lg, axis=-1).astype(jnp.int32),
                logits)
            # accepted = length of the leading draft prefix the sampled
            # chain reproduces (cand[q] is the token AFTER tokens[:q+1],
            # verified against draft tokens[q+1])
            ok = (cand[:, :-1] == tokens[:, 1:]) & \
                (jnp.arange(Q - 1)[None, :] < draft_lens[:, None])
            acc = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
            return pool, cand, acc

        def mixed_fn(params, pool, tokens, starts, q_lens, active,
                     block_tables, keys, sample_idx, temp, topk, topp,
                     lora):
            """ONE mixed prefill+decode dispatch (ISSUE 20): per-row
            ``starts``/``q_lens`` DEVICE operands carry each slot's role
            — a decode slot is a ``q_len == 1`` row sampling its next
            token, a mid-prefill prompt a ``q_len == n`` row scattering
            its chunk's KV from ``starts`` (= ``num_computed``); the
            sampled token is that prompt's FIRST token when the chunk
            completes it, discarded otherwise. Role churn never
            retraces: one executable per Q bucket serves every mix."""
            stats["mixed_traces"] += 1             # trace-time only
            logits, pool, _drops = G.paged_mixed_step(
                params, cfg, tokens, starts, q_lens, block_tables, pool,
                active, use_kernel=use_kernel, lora=lora)
            return pool, _next_tokens(logits, keys, sample_idx, temp,
                                      topk, topp)

        def sample_fn(logits, keys, idx, temp, topk, topp):
            """First-token sampler over a prefill wave's logits (one
            executable per wave-batch bucket, like prefill itself)."""
            stats["sample_traces"] += 1            # trace-time only
            kt = jax.vmap(jax.random.fold_in)(keys, idx)
            return G.sample_tokens(logits, kt, temp, topk, topp)

        if self._lora is None:
            # bind the LoRA operand away: the jitted surface (and under
            # TP the shard_map arity) is exactly the LoRA-less engine's
            import functools
            prefill_fn = functools.partial(prefill_fn, lora=None)
            chunk_fn = functools.partial(chunk_fn, lora=None)
            decode_fn = functools.partial(decode_fn, lora=None)
            spec_fn = functools.partial(spec_fn, lora=None)
            mixed_fn = functools.partial(mixed_fn, lora=None)
        if self._mesh is not None:
            # tensor parallelism: every pool-touching program runs under
            # shard_map on the replica's "tp" mesh — params enter at the
            # serving_param_specs layout (QKV column-sharded, the rest
            # replicated), the pool at its kv-heads split, and every
            # scheduler operand (tokens / tables / slot state / sampling
            # knobs / the iteration bound) REPLICATED, so the host-side
            # dispatch code below this point is identical at every tp.
            # The sampler (sample_fn) touches neither params nor pool and
            # stays a plain jit on the replicated prefill logits.
            from jax.sharding import PartitionSpec
            from ...core.jax_compat import shard_map
            from ...models.llama import serving_param_specs
            ps = serving_param_specs(self._params, self._mesh)
            zs = G.paged_pool_specs(self.cache.pool, self._mesh)
            R = PartitionSpec()
            if self._lora is not None:
                # the adapter pool shards like the projections it feeds
                # (qB/kB/vB on their output-feature axis, the rest
                # replicated); the per-row slot ids replicate like every
                # other scheduler operand
                from ...models.lora import lora_pool_specs
                ls = ({"ids": R,
                       "layers": lora_pool_specs(self._lora.layers,
                                                 self._mesh)},)
            else:
                ls = ()
            prefill_fn = shard_map(prefill_fn, mesh=self._mesh,
                                   in_specs=(ps, R, R, R, zs, R) + ls,
                                   out_specs=(R, zs, R), check_vma=False)
            chunk_fn = shard_map(chunk_fn, mesh=self._mesh,
                                 in_specs=(ps, R, R, R, R, zs) + ls,
                                 out_specs=(R, zs, R), check_vma=False)
            decode_fn = shard_map(decode_fn, mesh=self._mesh,
                                  in_specs=(ps, zs) + (R,) * 12 + ls,
                                  out_specs=(zs, R, R, R, R, R),
                                  check_vma=False)
            spec_fn = shard_map(spec_fn, mesh=self._mesh,
                                in_specs=(ps, zs) + (R,) * 11 + ls,
                                out_specs=(zs, R, R), check_vma=False)
            mixed_fn = shard_map(mixed_fn, mesh=self._mesh,
                                 in_specs=(ps, zs) + (R,) * 10 + ls,
                                 out_specs=(zs, R), check_vma=False)
        donate = donation_supported()
        jpre = jax.jit(prefill_fn, donate_argnums=(4,) if donate else ())
        jchk = jax.jit(chunk_fn, donate_argnums=(5,) if donate else ())
        jdec = jax.jit(decode_fn, donate_argnums=(1,) if donate else ())
        jspec = jax.jit(spec_fn, donate_argnums=(1,) if donate else ())
        jmix = jax.jit(mixed_fn, donate_argnums=(1,) if donate else ())
        jsamp = jax.jit(sample_fn)
        return jpre, jchk, jdec, jspec, jsamp, jmix

    def _build_embed(self, jax):
        """The prefill-only embeddings program (ISSUE 19): one jitted
        ``bert_encode`` forward, compiled per ``(batch, length)`` bucket
        exactly like the batched prefill. Plain jit even under TP — the
        encoder runs replicated (params and activations are tiny next to
        the LM's sharded KV traffic)."""
        from ...models.bert import bert_encode
        ecfg, stats = self._embed_cfg, self._stats

        def embed_fn(params, ids, lengths):
            stats["embed_traces"] += 1             # trace-time only
            return bert_encode(params, ecfg, ids, lengths)

        return jax.jit(embed_fn)

    def _lora_operand(self, ids) -> tuple:
        """The trailing LoRA dispatch operand: per-row adapter pool slots
        + the stacked pool leaves, or () with multi-adapter serving off
        (the programs were then partial-bound to ``lora=None``)."""
        if self._lora is None:
            return ()
        import jax.numpy as jnp
        return ({"ids": jnp.asarray(np.asarray(ids, np.int32)),
                 "layers": self._lora.layers},)

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    def _record_dispatch(self, kind: str, t0: float) -> None:
        """Count + time ONE device dispatch by kind (ISSUE 20). Every
        dispatch — batched prefill, prefill chunk, embed encode, decode
        loop, mixed step, spec verify — lands here, so ``chunks`` is the
        true all-kinds dispatch total (it previously only counted
        decode/verify dispatches: a prefill-only step reported zero
        dispatch work), the per-kind ``*_dispatches`` counters split it,
        and the wall time feeds the bounded window behind the p50/p99
        dispatch-latency rows in stats()/health_snapshot()."""
        self._stats["chunks"] += 1
        self._stats[kind + "_dispatches"] += 1
        self._dispatch_ms[kind].append((time.time() - t0) * 1e3)

    def _dispatch_latency(self) -> Dict[str, Dict[str, float]]:
        """p50/p99 dispatch wall time per kind over the recent window —
        the stall mixed batching removes, as a number operators watch."""
        out: Dict[str, Dict[str, float]] = {}
        for kind, window in self._dispatch_ms.items():
            n = int(self._stats.get(kind + "_dispatches", 0))
            if window:
                xs = np.asarray(window, np.float64)
                out[kind] = {
                    "count": n,
                    "p50_ms": round(float(np.percentile(xs, 50)), 3),
                    "p99_ms": round(float(np.percentile(xs, 99)), 3)}
            else:
                out[kind] = {"count": n, "p50_ms": None, "p99_ms": None}
        return out

    # ---- request lifecycle ------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = "unset",
               timeout_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None, priority: int = 0,
               temperature: Any = "unset", top_k: Any = "unset",
               top_p: Any = "unset", seed: Any = "unset",
               adapter_id: Optional[str] = None) -> int:
        """Queue one prompt; returns the request id. ``eos_token_id``
        defaults to the engine's GenerationConfig (pass ``None`` explicitly
        to disable EOS for this request).

        Sampling knobs (ISSUE 11) resolve through the ONE
        ``GenerationConfig`` struct (left unset -> the engine's
        ``gen_config`` defaults; explicit ``None`` DISABLES top_k/top_p):
        ``temperature`` 0 = greedy argmax on device, bit-identical to the
        greedy-only engine; > 0 samples with per-request PRNG keys
        derived from ``seed``, so the stream is reproducible per
        ``(request, seed)`` across preemption, crash resubmit and
        failover. Genuinely unsupported combinations (negative/non-finite
        temperature, ``top_k < 1``, ``top_p`` outside ``(0, 1]``) raise a
        structured ``ValueError`` naming the supported surface.

        Lifecycle/policy knobs (ISSUE 6): ``timeout_s`` (relative to now) /
        ``deadline_s`` (absolute ``time.time()``) bound the request's wall
        time — expiry while QUEUED sheds it (state ``shed``), expiry after
        it started terminates it mid-flight (state ``timed_out``), both
        freeing its KV blocks; the earlier of the two wins when both are
        given. ``tenant`` scopes fair-share scheduling, per-tenant stats
        and prefix-cache quotas; ``priority`` orders the priority policy
        (higher first).

        ``adapter_id`` (ISSUE 19) selects a registered LoRA adapter for
        this request (None = base traffic — the zeroed slot-0 adapter,
        bit-identical to the LoRA-less engine). The adapter must already
        be :meth:`register_adapter`-ed; admission pins it device-resident
        for the request's whole lifetime (preemption included), so its
        weights can never be evicted mid-stream.

        Raises :class:`ServingQueueFull` — carrying ``queue_depth`` /
        ``live_slots`` / ``retry_after_s`` for the caller's backoff — when
        the bounded admission queue is full: the submit is SHED, not
        blocked."""
        deadline = deadline_s
        if timeout_s is not None:
            t = time.time() + float(timeout_s)
            deadline = t if deadline is None else min(deadline, t)
        req = self._make_request(prompt, max_new_tokens, eos_token_id,
                                 tenant, priority, deadline,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p, seed=seed,
                                 adapter_id=adapter_id)
        with self._lock:
            rid = self._sched.submit(req)
            self._journal_submit(req)
            return rid

    def _make_request(self, prompt, max_new_tokens, eos_token_id, tenant,
                      priority, deadline, tokens: Sequence[int] = (),
                      temperature: Any = "unset", top_k: Any = "unset",
                      top_p: Any = "unset", seed: Any = "unset",
                      adapter_id: Optional[str] = None) -> Request:
        """One Request from user-facing arguments — the single place
        submit() and resubmit() resolve GenerationConfig defaults (the
        sampling knobs included), the "unset" sentinels and the tenant
        key, so fresh and crash-recovered requests can never diverge in
        defaults."""
        from ...models.generation import GenerationConfig, validate_sampling
        g = GenerationConfig.resolve(
            self._gen, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, temperature=temperature,
            top_k=top_k, top_p=top_p, seed=seed)
        validate_sampling(g)
        req = Request(
            rid=-1, prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(g.max_new_tokens),
            eos_token_id=g.eos_token_id,
            temperature=float(g.temperature),
            top_k=int(g.top_k) if g.top_k is not None else None,
            top_p=float(g.top_p) if g.top_p is not None else None,
            seed=int(g.seed),
            tenant=str(tenant) if tenant is not None else DEFAULT_TENANT,
            priority=int(priority),
            deadline=float(deadline) if deadline is not None else None)
        req.tokens = [int(t) for t in tokens]
        if req.tokens and req.eos_token_id is not None and \
                req.tokens[-1] == req.eos_token_id:
            req.eos_seen = True
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if req.prompt_len < 1:
            raise ValueError("prompt must contain at least one token")
        if adapter_id is not None:
            if self._lora is None:
                raise ValueError(
                    "adapter_id requires multi-adapter serving: set "
                    "ServingConfig.lora_slots / FLAGS_serving_lora_slots "
                    "> 0")
            if not self._lora.is_registered(adapter_id):
                raise ValueError(
                    f"adapter {adapter_id!r} is not registered on this "
                    f"engine (register_adapter() first; registered: "
                    f"{self._lora.registered()})")
            req.adapter_id = str(adapter_id)
        return req

    def resubmit(self, prompt, tokens: Sequence[int] = (),
                 max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = "unset",
                 deadline: Optional[float] = None,
                 tenant: Optional[str] = None, priority: int = 0,
                 temperature: Any = "unset", top_k: Any = "unset",
                 top_p: Any = "unset", seed: Any = "unset",
                 jid: Optional[int] = None,
                 adapter_id: Optional[str] = None) -> int:
        """Re-queue a request recovered from a torn-down engine with the
        tokens it had already emitted — the supervisor's restart path.
        Rides the preemption-recompute machinery: prefill recomputes KV
        for ``prompt + tokens[:-1]`` and decode resumes from the last
        token, so outputs are bit-identical to an uninterrupted run
        (greedy by determinism; sampled because the per-token key is a
        pure function of ``(seed, token index)`` — the caller passes the
        original RESOLVED sampling knobs) and the already-delivered
        tokens are never re-emitted. ``deadline`` is ABSOLUTE (the
        original request's). Bypasses the queue-depth shed — everything
        resubmitted was already accepted once, and the recovered set
        (old queue + old slots) can exceed the admission bound by up to
        ``max_slots``.

        ``jid`` re-attaches the request to an existing journal record
        (crash recovery / cross-replica failover under a shared journal):
        the record is resumed in place — no duplicate submit event — so
        recovery is idempotent across repeated crashes. An unknown or
        already-terminal jid falls back to a fresh journal record seeded
        with the delivered tokens."""
        req = self._make_request(prompt, max_new_tokens, eos_token_id,
                                 tenant, priority, deadline, tokens=tokens,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p, seed=seed,
                                 adapter_id=adapter_id)
        if req.finished:
            raise ValueError(
                f"request is already finished ({len(req.tokens)} tokens of "
                f"{req.max_new_tokens}); record it, don't resubmit it")
        with self._lock:
            rid = self._sched.submit(req, enforce_bound=False)
            self._journal_submit(req, jid)
            return rid

    # ---- durable journal hooks (ISSUE 18) ---------------------------------

    def _journal_submit(self, req: Request,
                        jid: Optional[int] = None) -> None:
        """Attach a just-admitted request to the journal: resume an
        existing record when ``jid`` names a live one (recovery /
        failover / adoption), else append a fresh submit event carrying
        the RESOLVED record. Caller holds the engine lock."""
        if self.journal is None:
            return
        if jid is not None and jid >= 0 \
                and self.journal.resume(jid, req.tokens):
            req.jid = jid
        else:
            req.jid = self.journal.log_submit(
                prompt=req.prompt, max_new_tokens=req.max_new_tokens,
                eos_token_id=req.eos_token_id,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed, tenant=req.tenant,
                priority=req.priority, deadline=req.deadline,
                tokens=req.tokens, adapter_id=req.adapter_id)
        self._jlive[req.rid] = req.jid

    def _journal_end(self, req: Request) -> None:
        """Journal a terminal transition the moment it happens (deadline
        expiry, cancel, shed) — a disowned request (jid -1) logs
        nothing. Caller holds the engine lock."""
        self._jlive.pop(req.rid, None)
        if self.journal is not None and req.jid >= 0:
            self.journal.log_terminal(req.jid, req.state)

    def _journal_step(self, emitted: Dict[int, List[int]]) -> None:
        """The per-step journal hook, run under the engine lock right
        after ``_step``: log every delivered-token cursor advance, log
        terminal transitions the retire sweep made, then flush — ONE
        fsync per step under the default policy, at exactly the boundary
        where the emitted tokens become visible to the caller."""
        if self.journal is None:
            return
        for rid, toks in emitted.items():
            jid = self._jlive.get(rid)
            if jid is not None and toks:
                self.journal.log_tokens(jid, toks)
        fin = self._sched.finished
        for rid in [r for r in self._jlive if r in fin]:
            req = fin[rid]
            self._jlive.pop(rid, None)
            if req.jid >= 0:
                self.journal.log_terminal(req.jid, req.state)
        self.journal.flush()

    def _journal_flush(self) -> None:
        if self.journal is not None:
            self.journal.flush()

    def journal_disown(self, rid: int) -> None:
        """Detach a live request from its journal record WITHOUT ending
        it — the deliberate same-fleet moves (migration release, prefill
        handoff release, hedge copies) cancel their vacated copy, and
        that cancel must not mark the still-live logical request
        terminal. The new owner re-attaches via :meth:`journal_own` or
        ``resubmit(jid=)``/``adopt``."""
        with self._lock:
            self._jlive.pop(rid, None)
            req = self._sched.find(rid)
            if req is not None:
                req.jid = -1

    def journal_own(self, rid: int, jid: int, tokens) -> bool:
        """Attach a live request to journal record ``jid`` (hedge
        promotion: the winning copy inherits the logical request's
        record), rebasing the record's delivered cursor to ``tokens`` —
        what the client actually saw. False when the record is unknown /
        terminal or the rid is not live."""
        with self._lock:
            if self.journal is None:
                return False
            req = self._sched.find(rid)
            if req is None or not self.journal.resume(jid, tokens):
                return False
            req.jid = int(jid)
            self._jlive[rid] = req.jid
            return True

    # ---- multi-adapter LoRA + embeddings endpoint (ISSUE 19) --------------

    def register_adapter(self, name: str, adapter_params) -> None:
        """Accept one LoRA adapter (host-side checksummed copy; rank must
        match ``lora_rank``) so requests may select it via
        ``submit(adapter_id=name)``. Re-registering an unpinned adapter
        replaces its weights; a pinned one (running requests) refuses."""
        with self._lock:
            if self._lora is None:
                raise ValueError(
                    "multi-adapter serving is off: set ServingConfig."
                    "lora_slots / FLAGS_serving_lora_slots > 0")
            self._lora.register(name, adapter_params)

    def adapter_registered(self, name: str) -> bool:
        with self._lock:
            return self._lora is not None and \
                self._lora.is_registered(name)

    def adapter_resident(self, name: str) -> bool:
        """Whether ``name`` is loaded in the device pool right now — the
        router's adapter-affinity signal (land a request where its
        adapter is already resident and skip the H2D load)."""
        with self._lock:
            return self._lora is not None and \
                self._lora.slot_of(name) is not None

    def adapter_partition(self) -> Optional[Dict[str, Any]]:
        """A consistent view of the adapter pool under the engine lock —
        what the InvariantAuditor's ``adapter_pool_partition`` check
        reads: every registered adapter is resident XOR evicted, every
        live request's adapter is resident at the slot the request
        carries, and every such request holds a pin. None with
        multi-adapter serving off."""
        with self._lock:
            if self._lora is None:
                return None
            running = {r.rid: (r.adapter_id, int(r.adapter_slot))
                       for r in self._sched.live
                       if r.adapter_id is not None}
            return {"registered": self._lora.registered(),
                    "resident": self._lora.resident(),
                    "evicted": self._lora.evicted(),
                    "pinned": self._lora.pinned(),
                    "running": running}

    def submit_embedding(self, prompt, timeout_s: Optional[float] = None,
                         deadline_s: Optional[float] = None,
                         tenant: Optional[str] = None,
                         priority: int = 0) -> int:
        """Queue one prefill-only EMBEDDING request (ISSUE 19): it rides
        the admission queue (bounded — sheds with ServingQueueFull like
        generate traffic), runs through the attached encoder in the next
        step's batched bucketed dispatch, and retires at prefill
        completion with the pooled hidden states readable via
        :meth:`embedding`. Embeds hold no decode slot and no KV blocks
        and are NOT journaled — they carry no generation state, so a
        crash loses nothing a stateless client retry cannot recompute."""
        if self._embed_params is None:
            raise ValueError(
                "no embedding model attached: construct the engine with "
                "embed_model=(BertConfig, params) to serve embeddings")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must contain at least one token")
        if prompt.shape[0] > self._embed_cfg.max_position_embeddings:
            raise ValueError(
                f"embedding prompt has {prompt.shape[0]} tokens > the "
                f"encoder's max_position_embeddings "
                f"{self._embed_cfg.max_position_embeddings}")
        deadline = deadline_s
        if timeout_s is not None:
            t = time.time() + float(timeout_s)
            deadline = t if deadline is None else min(deadline, t)
        req = Request(
            rid=-1, prompt=prompt, max_new_tokens=1,
            tenant=str(tenant) if tenant is not None else DEFAULT_TENANT,
            priority=int(priority),
            deadline=float(deadline) if deadline is not None else None,
            kind="embed")
        with self._lock:
            return self._sched.submit(req)

    def embedding(self, rid: int) -> np.ndarray:
        """The pooled ``[hidden_size]`` fp32 embedding of a finished
        embed request (KeyError while still queued/in-flight)."""
        with self._lock:
            return self._sched.finished[rid].embedding

    # ---- live KV migration (ISSUE 16) -------------------------------------

    def kv_shape_key(self) -> tuple:
        """The KV-layout signature two engines must share for a block
        chain to transfer byte-for-byte: block size, quantization mode,
        TP degree and every pool leaf's per-block shape/dtype (the block
        axis itself excluded — pools of different sizes interoperate).
        In a shared-programs fleet these always agree; :meth:`adopt`
        refuses a mismatched payload so a heterogeneous fleet falls back
        to resubmit instead of writing garbage KV."""
        return (int(self.config.block_size), str(self.config.kv_quant),
                int(self.config.tp),
                tuple(sorted((name, str(a.dtype),
                              tuple(int(s) for i, s in enumerate(a.shape)
                                    if i != 1))
                             for name, a in self.cache.pool.items())))

    def serialize_request(self, rid: int) -> Optional[Dict[str, Any]]:
        """Snapshot one live request for adoption by another replica: the
        resolved record (prompt, delivered tokens, sampling knobs, tenant
        / priority / deadline) plus — for a request holding a slot — its
        KV block chain's device bytes (one gather per pool leaf over the
        blocks with committed entries, materialized D2H). Returns None
        for unknown/terminal requests and for finished ones awaiting the
        retire sweep (their work is done; migrating it would re-deliver).
        Queued and preempted-requeued requests serialize with ``kv:
        None`` — they hold no KV, so adoption degrades to a plain
        resubmit of the record."""
        with self._lock:
            req = self._sched.find(rid)
            if req is None or req.terminal or req.finished:
                return None
            payload: Dict[str, Any] = {
                "prompt": np.array(req.prompt, np.int32),
                "tokens": list(req.tokens),
                "max_new_tokens": req.max_new_tokens,
                "eos_token_id": req.eos_token_id,
                "temperature": req.temperature,
                "top_k": req.top_k, "top_p": req.top_p, "seed": req.seed,
                "tenant": req.tenant, "priority": req.priority,
                "deadline": req.deadline,
                "jid": req.jid,
                "adapter_id": req.adapter_id,
                "kv": None,
            }
            if req.slot is None or not req.blocks:
                return payload
            if req.prefilling:
                entries = int(req.num_computed)
            else:
                entries = int(self._seq_lens[req.slot])
            bs = self.config.block_size
            nd = min(-(-entries // bs), len(req.blocks)) if entries else 0
            data = None
            if nd:
                idx = np.asarray(req.blocks[:nd], np.int32)
                data = {name: np.asarray(arr[:, idx])
                        for name, arr in self.cache.pool.items()}
            payload["kv"] = {
                "entries": entries,
                "prefilling": bool(req.prefilling),
                "data_blocks": nd,
                "total_blocks": len(req.blocks),
                "data": data,
                "shape_key": self.kv_shape_key(),
            }
            return payload

    def adopt(self, payload: Dict[str, Any]) -> int:
        """Adopt a request serialized on another replica, KV included:
        allocate the chain, H2D-write the committed blocks, seat the
        request directly in a RUNNING slot (mid-chunked-prefill resumes
        at its chunk offset; decoding resumes from its last token with
        the sampling cursor continuing at the same PRNG index, so the
        stream stays bit-identical) and re-register the chain's prefix
        keys. Raises :class:`AdoptError` when the blocks can't land here
        — no free slot, pool full, KV-layout/TP-shape mismatch — and the
        caller falls back to the resubmit/recompute path. A ``kv: None``
        payload (queued/preempted origin) is queued via the resubmit
        path directly."""
        with self._lock:
            aid = payload.get("adapter_id")
            if aid is not None and (self._lora is None
                                    or not self._lora.is_registered(aid)):
                raise AdoptError(
                    f"adapter {aid!r} is not registered on this replica; "
                    f"falling back to resubmit")
            req = self._make_request(
                payload["prompt"], payload["max_new_tokens"],
                payload["eos_token_id"], payload["tenant"],
                payload["priority"], payload["deadline"],
                tokens=payload["tokens"],
                temperature=payload["temperature"],
                top_k=payload["top_k"], top_p=payload["top_p"],
                seed=payload["seed"], adapter_id=aid)
            if req.finished:
                raise AdoptError("request already finished; record it, "
                                 "don't migrate it")
            kv = payload.get("kv")
            if kv is None:
                rid = self._sched.submit(req, enforce_bound=False)
                self._journal_submit(req, payload.get("jid"))
                return rid
            if tuple(kv["shape_key"]) != self.kv_shape_key():
                raise AdoptError("KV layout mismatch (block size / "
                                 "kv_quant / TP shape differ); falling "
                                 "back to resubmit")
            if req.kv_tokens > self.cache.max_model_len:
                raise AdoptError("chain exceeds this engine's "
                                 "max_model_len")
            free = [m for m, r in enumerate(self._sched.slots) if r is None]
            if not free:
                raise AdoptError("no free decode slot")
            total = int(kv["total_blocks"])
            if total > self.cache.blocks_per_seq:
                raise AdoptError("chain longer than the block table")
            if not self.cache.manager.can_alloc(total):
                raise AdoptError("pool full")
            blocks = self.cache.manager.alloc(total)
            nd = int(kv["data_blocks"])
            try:
                if nd:
                    self.cache.write_blocks(blocks[:nd], kv["data"])
            except Exception as e:
                self.cache.manager.free(blocks)
                raise AdoptError(f"KV restore failed: {e}")
            if req.adapter_id is not None:
                # pin the adapter resident BEFORE seating: a fully pinned
                # pool refuses the migration (recompute elsewhere beats
                # evicting someone's in-flight weights)
                aslot = self._lora.acquire(req.adapter_id)
                if aslot is None:
                    self.cache.manager.free(blocks)
                    raise AdoptError(
                        f"adapter pool fully pinned; cannot seat adapter "
                        f"{req.adapter_id!r} — falling back to resubmit")
                req.adapter_slot = aslot
            slot = free[0]
            self._clear_slot(slot)
            self._sched.adopt_running(req, slot, blocks)
            if req.adapter_id is not None:
                self._lora_pinned[req.rid] = req.adapter_id
            self.cache.assign(slot, blocks)
            entries = int(kv["entries"])
            if kv["prefilling"]:
                # resume the chunked prefill exactly at its chunk offset:
                # _advance_prefills picks the slot up next step
                req.prefill_ids = req.build_prefill_ids()
                req.num_computed = entries
            else:
                req.prefill_ids = None
                self._start_decode(req)
            # re-derive the prefix-cache registration chain (the chained
            # content keys are a pure function of the token ids, so the
            # adopted blocks register under exactly the origin's keys)
            req.reg_state = self.cache.register_prefix(
                req.build_prefill_ids(), blocks, entries,
                tenant=req.tenant, namespace=req.adapter_id)
            self._journal_submit(req, payload.get("jid"))
            return req.rid

    # ---- fleet-wide cache pulls (ISSUE 17) --------------------------------

    def export_chain(self, chain) -> Optional[Dict[str, Any]]:
        """Serialize the longest CONTIGUOUS prefix of ``chain`` — a list
        of ``(key, tokens)`` pairs in :func:`~.paged_cache.
        prefix_block_chain` order — that this replica holds: device
        blocks gather D2H through :meth:`PagedKVCache.read_block` (the
        device-scalar index discipline, one compiled slice program),
        host-tier blocks come from a verified non-destructive
        :meth:`HostOffloadTier.peek`. Every block's leaves are stamped
        with a write-time CRC32 (the ``offload.py`` checksum), so the
        receiving :meth:`graft_chain` can detect any corruption in
        flight and degrade to recompute — never wrong KV. The export is
        a COPY: refcounts, registrations and tier entries on this
        replica are untouched. Returns None when not even the first key
        resolves (a stale directory entry — the benign miss)."""
        with self._lock:
            blocks: List[Dict[str, Any]] = []
            for key, toks in chain:
                toks = tuple(int(t) for t in toks)
                data = None
                b = self.cache.manager.lookup(key, toks)
                if b is not None:
                    data = {name: np.asarray(arr)
                            for name, arr in
                            self.cache.read_block(b).items()}
                elif self.cache.offload is not None:
                    hit = self.cache.offload.peek(key, toks)
                    if hit is not None:
                        data = {name: np.array(arr) for name, arr
                                in hit.items()}
                if data is None:
                    break                 # contiguity ends at first miss
                blocks.append({"key": int(key), "tokens": toks,
                               "data": data,
                               "crc": {n: _block_crc(a)
                                       for n, a in data.items()}})
            if not blocks:
                return None
            if self._corrupt_next_export:
                # chaos drill: flip one byte AFTER the checksums stamped
                self._corrupt_next_export = False
                leaf = sorted(blocks[0]["data"])[0]
                arr = np.array(blocks[0]["data"][leaf], copy=True)
                arr.reshape(-1).view(np.uint8)[0] ^= 0xFF
                blocks[0]["data"][leaf] = arr
            return {"blocks": blocks, "shape_key": self.kv_shape_key()}

    def graft_chain(self, payload: Dict[str, Any]) -> Dict[str, int]:
        """Graft an exported chain into this replica's prefix cache:
        verify each block's checksums, allocate a device block,
        H2D-write the bytes and register the chain key — the block then
        parks refcount-0 on the evictable list exactly like a locally
        computed cached block, where the next ``admit()`` hits it. Walks
        in chain order and STOPS at the first checksum mismatch (the
        rest of the chain is downstream of corrupt KV), already-present
        key, or dry pool. Returns ``{"grafted", "present", "corrupt"}``
        — the caller's submit degrades to recompute for whatever did
        not land, so a failed pull can only cost time."""
        counts = {"grafted": 0, "present": 0, "corrupt": 0}
        if payload is None:
            return counts
        with self._lock:
            if tuple(payload["shape_key"]) != self.kv_shape_key():
                raise AdoptError("KV layout mismatch (block size / "
                                 "kv_quant / TP shape differ); pull "
                                 "falls back to recompute")
            for ent in payload["blocks"]:
                key, toks = int(ent["key"]), tuple(ent["tokens"])
                if self.cache.manager._hash2block.get(key) is not None:
                    counts["present"] += 1
                    continue              # first writer won locally
                bad = any(_block_crc(np.asarray(a)) != ent["crc"][n]
                          for n, a in ent["data"].items())
                if bad:
                    counts["corrupt"] += 1
                    break
                if not self.cache.manager.can_alloc(1):
                    break                 # pool pressure: partial graft
                [b] = self.cache.manager.alloc(1)
                self.cache.write_block(b, ent["data"])
                self.cache.manager.register(key, b, toks)
                # release to the evictable list: cached, shareable, and
                # reclaimable under pressure — never a leak at quiesce
                self.cache.manager.free([b])
                counts["grafted"] += 1
            return counts

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or running request: its remaining work is
        dropped and every KV block it holds returns to the pool
        immediately (the preemption free path — free, do NOT requeue).
        Safe at any lifecycle point — queued, mid-chunked-prefill,
        decoding, or preempted-and-requeued. Returns True when the
        request was live and is now ``cancelled``; False when it already
        reached a terminal state (or the rid is unknown) — cancellation
        is idempotent, racing a retirement is not an error. The partial
        output stays readable via :meth:`request`/``result``."""
        with self._lock:
            req = self._sched.find(rid)
            if req is None:
                return False
            if self._retire_if_finished(req):
                return False         # its work completed first: not an error
            self._terminate(req, CANCELLED)
            self._journal_flush()
            return True

    def cancel_all(self) -> int:
        """Cancel every queued and running request (the abandoned-stream
        path); returns how many were cancelled."""
        with self._lock:
            n = 0
            for req in list(self._sched.queue) + self._sched.live:
                if self._retire_if_finished(req):
                    continue
                self._terminate(req, CANCELLED)
                n += 1
            if n:
                self._journal_flush()
            return n

    # ---- adapter pin lifecycle (ISSUE 19) ---------------------------------

    def _lora_gate(self, req: Request) -> bool:
        """The scheduler's admission gate: pin the pick's adapter
        device-resident (loading it over the LRU unpinned victim when
        cold) and stamp its pool slot on the request. False — skip this
        pick, no head-of-line blocking — when every pool slot is pinned
        by other running requests. Idempotent per request: a pick that
        pinned but then waited for KV blocks (or was preempted) keeps
        its pin and slot."""
        if req.adapter_id is None:
            req.adapter_slot = 0
            return True
        if req.rid in self._lora_pinned:
            return True
        slot = self._lora.acquire(req.adapter_id)
        if slot is None:
            return False
        self._lora_pinned[req.rid] = req.adapter_id
        req.adapter_slot = slot
        return True

    def _lora_release(self, req: Request) -> None:
        """Drop a terminal request's adapter pin (the adapter stays
        resident-warm until the LRU needs its slot)."""
        if self._lora is None:
            return
        name = self._lora_pinned.pop(req.rid, None)
        if name is not None:
            self._lora.release(name)

    def _lora_sweep(self) -> None:
        """Release pins whose requests the retire sweep finished — the
        step-boundary companion to the explicit terminal-path releases,
        mirroring how ``_journal_step`` collects finished jids."""
        if self._lora is None or not self._lora_pinned:
            return
        fin = self._sched.finished
        for rid in [r for r in self._lora_pinned if r in fin]:
            self._lora.release(self._lora_pinned.pop(rid))

    def _retire_if_finished(self, req: Request) -> bool:
        """A request can sit FINISHED in its slot until the next step's
        retire sweep (e.g. oom-truncated with no decode dispatch after
        it); a cancel or deadline racing that sweep must retire it as the
        completed work it is, never reclassify it. Only slot-holders can
        be in this state — a queued request has produced nothing to
        finish."""
        if req.slot is None or not req.finished:
            return False
        m = req.slot
        self._sched.finish(req)
        self._clear_slot(m)
        self._lora_release(req)
        self._journal_end(req)
        return True

    def _clear_slot(self, m: int) -> None:
        self._tokens[m] = 0
        self._seq_lens[m] = 0
        self._steps_left[m] = 0
        self._done[m] = True
        self._eos[m] = -1
        self._temp[m] = 0.0
        self._topk[m] = 0
        self._topp[m] = 1.0
        self._keys[m] = 0
        self._sample_idx[m] = 0
        self._adapters[m] = 0

    def _terminate(self, req: Request, state: str) -> None:
        m = req.slot
        self._sched.terminate(req, state)
        if m is not None:
            self._clear_slot(m)
        self._lora_release(req)
        self._journal_end(req)

    def _expire_deadlines(self, now: float) -> None:
        """Terminal-state sweep, run once per step and only while some
        live request carries a deadline: queued requests past theirs are
        SHED (they never ran — admission control, the client should back
        off), except preempted ones which already ran and so TIME OUT;
        running requests past theirs TIME OUT, freeing their blocks
        mid-flight so a stuck consumer can never pin the pool."""
        if not self._sched.deadline_requests:
            return
        for req in [r for r in self._sched.queue
                    if r.deadline is not None and r.deadline < now]:
            self._terminate(req,
                            SHED if not (req.preemptions or req.tokens)
                            else TIMED_OUT)
        # a request that already FINISHED but has not been swept by
        # retire_finished yet (e.g. oom-truncated with no decode dispatch
        # after it) keeps its completed record — its work is done, an
        # expired deadline must not reclassify it as timed out
        for req in [r for r in self._sched.live
                    if r.deadline is not None and r.deadline < now
                    and not r.finished]:
            self._terminate(req, TIMED_OUT)

    def _chain_ids(self, req: Request, start: int, stop: int) -> np.ndarray:
        """Token ids backing the KV entries ``[start, stop)`` a running
        request has written (entry p < prompt_len holds prompt[p]'s KV,
        entry p >= prompt_len holds tokens[p - prompt_len]'s) — the
        prefix-cache registration chain. Sliced, not the whole history:
        rebuilding prompt+tokens per filled block would cost O(seq_len^2)
        per request in the continuous-batching hot loop."""
        pl = len(req.prompt)
        if stop <= pl:
            return req.prompt[start:stop]
        gen = np.asarray(req.tokens[max(0, start - pl):stop - pl], np.int32)
        if start >= pl:
            return gen
        return np.concatenate([req.prompt[start:], gen])

    def _start_decode(self, req: Request) -> None:
        """Move a request whose prefill just completed into the decode slot
        arrays. Fresh requests enter with their first sampled token already
        in ``tokens``; readmitted ones resume from their last token — and
        from their next SAMPLE INDEX, so the per-index PRNG keys line up
        with an uninterrupted run."""
        from ...models.generation import seed_key
        m = req.slot
        self._tokens[m] = req.tokens[-1]
        self._seq_lens[m] = req.prompt_len + len(req.tokens) - 1
        self._steps_left[m] = req.max_new_tokens - len(req.tokens)
        self._done[m] = False
        self._eos[m] = -1 if req.eos_token_id is None else req.eos_token_id
        self._temp[m] = req.temperature
        self._topk[m] = req.top_k if req.top_k is not None else 0
        self._topp[m] = req.top_p if req.top_p is not None else 1.0
        self._keys[m] = seed_key(req.seed)
        self._sample_idx[m] = len(req.tokens)
        self._adapters[m] = req.adapter_slot

    def _emit_first(self, req: Request, tok0: int, now: float,
                    emitted: Dict[int, List[int]]) -> None:
        req.first_token_t = now
        req.tokens.append(tok0)
        emitted.setdefault(req.rid, []).append(tok0)
        if req.eos_token_id is not None and tok0 == req.eos_token_id:
            req.eos_seen = True
        if req.finished:
            self._sched.finish(req)
        else:
            self._start_decode(req)

    def _admit(self, emitted: Dict[int, List[int]]) -> None:
        import jax.numpy as jnp
        self._admit_embeds()
        gate = self._lora_gate if self._lora is not None else None
        admitted: List[Request] = []
        while (req := self._sched.next_admission(gate=gate)) is not None:
            admitted.append(req)
        if not admitted:
            return
        # split the wave: COLD short prompts take the batched bucketed
        # prefill (one dispatch per power-of-2 length bucket, batch dim
        # padded to the wave-size bucket); prefix-cache hits (prefill
        # starts at an offset), long prompts (chunked), and readmissions
        # (recompute) go through the offset chunk path, one row at a time
        chunk = self.config.prefill_chunk
        fast = [r for r in admitted
                if r.num_computed == 0 and not r.tokens
                and (chunk is None or r.prompt_len <= chunk)]
        M = self.config.max_slots
        by_bucket: Dict[int, List[Request]] = {}
        for req in fast:
            by_bucket.setdefault(self._bucket(req.prompt_len), []).append(req)
        for Sb, group in sorted(by_bucket.items()):
            self._prefill_buckets.add(Sb)
            Bb = 1
            while Bb < len(group):
                Bb *= 2
            Bb = min(Bb, M)
            ids = np.zeros((Bb, Sb), np.int32)
            plens = np.ones((Bb,), np.int32)      # pad rows: harmless len 1
            tables = np.zeros((Bb, self.cache.blocks_per_seq), np.int32)
            act = np.zeros((Bb,), bool)
            aids = np.zeros((Bb,), np.int32)      # pad rows: base adapter
            for r, req in enumerate(group):
                ids[r, :req.prompt_len] = req.prompt
                plens[r] = req.prompt_len
                tables[r] = self.cache.tables[req.slot]
                act[r] = True
                aids[r] = req.adapter_slot
            t0 = time.time()
            with _watchdog.section("serving.prefill"):
                logits, self.cache.pool, _ = self._jprefill(
                    self._params, jnp.asarray(ids), jnp.asarray(plens),
                    jnp.asarray(tables), self.cache.pool, jnp.asarray(act),
                    *self._lora_operand(aids))
                first = self._first_tokens(logits, group, Bb)
            self._record_dispatch("prefill", t0)
            now = time.time()
            for r, req in enumerate(group):
                req.num_computed = req.prompt_len
                req.reg_state = self.cache.register_prefix(
                    req.prompt, req.blocks, req.prompt_len, req.reg_state,
                    tenant=req.tenant, namespace=req.adapter_id)
                self._emit_first(req, int(first[r]), now, emitted)
        # chunked/offset admissions advance via _advance_prefills

    def _admit_embeds(self) -> None:
        """Drain every queued embedding request (ISSUE 19) through the
        batched encoder: one jitted ``bert_encode`` dispatch per
        power-of-2 ``(batch, length)`` bucket, exactly the batched-
        bucketed-prefill shape discipline. The whole batch admits,
        encodes and FINISHES inside this locked step — embeds hold no
        decode slot and no KV blocks, so no observer ever sees one
        mid-flight."""
        if self._embed_params is None:
            return
        import jax.numpy as jnp
        group = self._sched.admit_embeds()
        if not group:
            return
        by_bucket: Dict[int, List[Request]] = {}
        for req in group:
            by_bucket.setdefault(self._bucket(req.prompt_len),
                                 []).append(req)
        for Sb, grp in sorted(by_bucket.items()):
            Bb = 1
            while Bb < len(grp):
                Bb *= 2
            ids = np.zeros((Bb, Sb), np.int32)
            lens = np.zeros((Bb,), np.int32)      # pad rows: length 0
            for r, req in enumerate(grp):
                ids[r, :req.prompt_len] = req.prompt
                lens[r] = req.prompt_len
            t0 = time.time()
            with _watchdog.section("serving.prefill"):
                pooled = np.asarray(self._jembed(
                    self._embed_params, jnp.asarray(ids),
                    jnp.asarray(lens)))
            self._record_dispatch("prefill", t0)
            now = time.time()
            for r, req in enumerate(grp):
                req.embedding = pooled[r]
                req.first_token_t = now
                self._stats["embeds"] += 1
                self._sched.finish(req)

    def _advance_prefills(self, emitted: Dict[int, List[int]]) -> None:
        """One prefill chunk per mid-prefill slot (offset path, B=1):
        long admissions make progress WITHOUT freezing the decode slots —
        the decode dispatch between chunks is what kills head-of-line
        pressure. Completing requests emit their first token (fresh) or
        resume from their kept tokens (post-preemption recompute)."""
        import jax.numpy as jnp
        chunk = self.config.prefill_chunk
        for req in [r for r in self._sched.live if r.prefilling]:
            total = len(req.prefill_ids)
            n = total - req.num_computed
            if chunk is not None:
                n = min(n, chunk)
            Sb = self._bucket(n)
            ids = np.zeros((1, Sb), np.int32)
            ids[0, :n] = req.prefill_ids[req.num_computed:
                                         req.num_computed + n]
            t0 = time.time()
            with _watchdog.section("serving.prefill"):
                logits, self.cache.pool, _ = self._jchunk(
                    self._params, jnp.asarray(ids),
                    jnp.asarray(req.num_computed, jnp.int32),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(self.cache.tables[req.slot][None]),
                    self.cache.pool,
                    *self._lora_operand([req.adapter_slot]))
            self._record_dispatch("prefill", t0)
            req.num_computed += n
            req.reg_state = self.cache.register_prefix(
                req.prefill_ids, req.blocks, req.num_computed,
                req.reg_state, tenant=req.tenant,
                namespace=req.adapter_id)
            if req.prefilling:
                continue                          # more chunks to go
            if req.tokens:                        # readmission: resume
                self._start_decode(req)
            else:
                tok0 = int(self._first_tokens(logits, [req], 1)[0])
                self._emit_first(req, tok0, time.time(), emitted)

    def _first_tokens(self, logits, group, Bb: int) -> np.ndarray:
        """Sample each admitted request's FIRST token (sample index 0)
        from its prefill logits. All-greedy waves take the literal host
        argmax (the v1 path, bitwise); a wave with any sampling row runs
        the compiled per-row sampler — greedy rows inside it still argmax
        through sample_tokens' where-select."""
        if all(r.temperature == 0.0 for r in group):
            return np.argmax(np.asarray(logits), axis=-1)
        import jax.numpy as jnp

        from ...models.generation import seed_key
        keys = np.zeros((Bb, 2), np.uint32)
        temp = np.zeros((Bb,), np.float32)
        topk = np.zeros((Bb,), np.int32)
        topp = np.ones((Bb,), np.float32)
        for r, req in enumerate(group):
            keys[r] = seed_key(req.seed)
            temp[r] = req.temperature
            topk[r] = req.top_k if req.top_k is not None else 0
            topp[r] = req.top_p if req.top_p is not None else 1.0
        return np.asarray(self._jsample(
            logits, jnp.asarray(keys), jnp.zeros((Bb,), jnp.int32),
            jnp.asarray(temp), jnp.asarray(topk), jnp.asarray(topp)))

    # ---- decode dispatch sizing -------------------------------------------

    def _limit(self, decoding, max_iters: Optional[int]) -> int:
        """Iterations for the next decode dispatch. Queue waiting or a
        prompt mid-chunked-prefill: run to the FIRST budget retirement
        (admit with zero idle iterations) and cap at ``decode_chunk`` so
        prefill chunks interleave. Queue empty: drain the whole tail in
        one dispatch (the in-graph alive-mask exit handles rows finishing
        early). ``decode_chunk`` also caps when a live row can retire
        EARLIER than its budget (EOS enabled) so admission latency stays
        bounded, or when the caller asked for streaming granularity via
        ``max_iters``."""
        sl = [int(self._steps_left[r.slot]) for r in decoding]
        prefilling = any(r.prefilling for r in self._sched.live)
        waiting = bool(self._sched.queue) or prefilling
        n = min(sl) if waiting else max(sl)
        if prefilling or (max_iters is None and
                          any(r.eos_token_id is not None
                              for r in decoding)):
            max_iters = min(max_iters or self.config.decode_chunk,
                            self.config.decode_chunk)
        if max_iters is not None:
            n = min(n, int(max_iters))
        return max(1, min(n, self._out_width))

    def _ensure_blocks(self, want: int) -> int:
        """Make the pool cover ``want`` decode iterations for every
        decoding slot — each needs blocks for ``seq_len + min(want,
        steps_left)`` KV entries. Returns the feasible iteration count
        (shrunk to what the pool can back), PREEMPTING the newest-admitted
        live request (never the oldest — that's the no-livelock proof)
        whenever even one iteration doesn't fit. If the sole survivor
        still can't get a block the pool is truly exhausted relative to
        its budget: it is retired early with ``oom_truncated`` set rather
        than hung."""
        bf = self.cache.manager.blocks_for

        while True:
            decoding = self._sched.decoding
            if not decoding:
                return 0

            def need(k: int) -> int:
                tot = 0
                for r in decoding:
                    e = int(self._seq_lens[r.slot]) + \
                        min(k, int(self._steps_left[r.slot]))
                    tot += max(0, bf(e) - len(r.blocks))
                return tot

            avail = self.cache.free_blocks
            if need(1) <= avail:
                lo, hi = 1, max(1, want)
                while lo < hi:                    # largest feasible k
                    mid = (lo + hi + 1) // 2
                    if need(mid) <= avail:
                        lo = mid
                    else:
                        hi = mid - 1
                for r in decoding:
                    e = int(self._seq_lens[r.slot]) + \
                        min(lo, int(self._steps_left[r.slot]))
                    if self.cache.extend(r.slot, r.blocks, e) is None:
                        break                     # raced an estimate; retry
                else:
                    return lo
                continue
            if not self._relieve_pressure(decoding):
                return 0

    def _relieve_pressure(self, decoding: List[Request]) -> bool:
        """The pool can't cover even the minimal next dispatch: preempt
        the newest-admitted live request (never the oldest — the
        no-livelock proof) and return True so the caller replans; with
        nothing left to preempt the sole survivor's budget exceeds the
        whole pool — truncate it (retire with the tokens it has, never
        hang the drain loop) and return False. The ONE preempt/truncate
        ladder the decode and spec block planners share."""
        victim = self._sched.preempt_victim()
        if victim is not None:
            self._preempt(victim)
            return True
        r = decoding[0]
        r.oom_truncated = True
        self._sched.oom_truncated += 1
        self._done[r.slot] = True
        return False

    def _preempt(self, req: Request) -> None:
        m = req.slot
        self._sched.preempt(req)
        self._clear_slot(m)

    # ---- speculative decoding (ISSUE 11) ----------------------------------

    def _ctx_at(self, req: Request, i: int) -> int:
        """Token backing context position ``i`` (prompt, then generated)
        without materializing the concatenation."""
        pl = req.prompt_len
        return int(req.prompt[i]) if i < pl else int(req.tokens[i - pl])

    def _draft_tokens(self, req: Request) -> List[int]:
        """n-gram prompt-lookup drafting (no second model): when the last
        ``spec_ngram`` tokens of the request's context (prompt +
        generated) reoccur earlier, propose the continuation of the most
        recent PRIOR occurrence — preferring one with a full
        ``spec_decode`` window of continuation. Capped so the verify can
        never emit past the token budget (``draft <= steps_left - 1``:
        emission is ``accepted + 1``). Returns [] when nothing matches —
        the step then falls through to the plain decode dispatch.

        An incremental per-request n-gram presence index (O(1) amortized
        per generated token) gates the scan: when the trailing n-gram
        has never occurred before, the miss costs O(ngram), not
        O(context) — so incoherent/long-context traffic pays nothing per
        step. The full O(context) occurrence scan (which preserves the
        exact most-recent/full-window selection) only runs when a draft
        WILL be proposed — steps where a verify dispatch is about to pay
        for itself anyway."""
        k = min(self._spec_k, int(self._steps_left[req.slot]) - 1)
        if k < 1:
            return []
        n = self._spec_n
        L = req.prompt_len + len(req.tokens)
        if L <= n:
            return []
        st = req.spec_index
        if st is None:
            st = req.spec_index = {"end": n - 1, "seen": set()}
        # index every n-gram ENDING at positions (st["end"], L-1] — one
        # tuple per newly appended token since the last call
        for e in range(st["end"] + 1, L):
            st["seen"].add(tuple(self._ctx_at(req, e - n + j)
                                 for j in range(n)))
        st["end"] = L - 1
        tail = tuple(self._ctx_at(req, L - n + j) for j in range(n))
        if tail not in st["seen"]:
            return []
        ctx = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        pat = ctx[-n:]
        win = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
        hits = np.nonzero((win == pat).all(axis=1))[0]
        if not hits.size:                  # unreachable given the index;
            return []                      # kept as a safety net
        # prefer the most recent occurrence with k tokens of continuation
        # inside the context; fall back to the most recent one at all
        full = hits[hits + n + k <= len(ctx)]
        j = int(full[-1]) if full.size else int(hits[-1])
        return [int(t) for t in ctx[j + n:j + n + k]]

    def _ensure_blocks_spec(self, drafts: Dict[int, List[int]]
                            ) -> List[Request]:
        """Block planning for one verify dispatch: every decoding slot
        needs blocks covering ``seq_len + draft_len + 1`` KV entries (the
        verify writes the last token's KV plus one per draft). When the
        pool can't cover the drafts they are DROPPED first — the caller
        then falls through to the plain decode loop, which batches
        iterations far cheaper than a pad-lane verify would — before any
        preemption; the preempt/truncate ladder is the shared
        :meth:`_relieve_pressure`. Returns the decoding set (possibly
        shrunk by preemption; empty = nothing to do)."""
        bf = self.cache.manager.blocks_for

        while True:
            decoding = self._sched.decoding
            if not decoding:
                return []

            def need(with_drafts: bool) -> int:
                tot = 0
                for r in decoding:
                    dl = len(drafts.get(r.rid, ())) if with_drafts else 0
                    e = int(self._seq_lens[r.slot]) + dl + 1
                    tot += max(0, bf(e) - len(r.blocks))
                return tot

            avail = self.cache.free_blocks
            if need(True) <= avail:
                with_drafts = True
            elif need(False) <= avail:
                with_drafts = False
                drafts.clear()         # pool-pressure fallback: no drafts
            elif self._relieve_pressure(decoding):
                continue
            else:
                return []
            for r in decoding:
                dl = len(drafts.get(r.rid, ())) if with_drafts else 0
                e = int(self._seq_lens[r.slot]) + dl + 1
                if self.cache.extend(r.slot, r.blocks, e) is None:
                    break                     # raced an estimate; retry
            else:
                return decoding

    def _rollback_blocks(self, req: Request) -> None:
        """Free the surplus blocks a verify's REJECTED tail left behind:
        after acceptance the slot's committed KV spans ``seq_len``
        entries, so any block past ``blocks_for(seq_len)`` holds only
        stale draft KV — it returns to the ref-counted manager through
        the same free path preemption uses (never a registered block:
        registration stops at the last committed full block). The stale
        entries INSIDE the kept tail block are overwritten by the next
        dispatch's write at ``seq_len`` or hidden by the ``j <= seq_len``
        mask."""
        keep = self.cache.manager.blocks_for(int(self._seq_lens[req.slot]))
        tail = req.blocks[keep:]
        if not tail:
            return
        self.cache.manager.free(tail)
        del req.blocks[keep:]
        self.cache.tables[req.slot, keep:] = 0

    def _spec_dispatch(self, decoding: List[Request],
                       drafts: Dict[int, List[int]],
                       emitted: Dict[int, List[int]]) -> None:
        """One speculative verify: build the ``[M, Q]`` token matrix
        (last token + drafts, pad lanes repeat the last token), dispatch
        the compiled verify program, then commit ``accepted + 1`` tokens
        per slot (EOS truncates), advance the sampling cursor, register
        freshly-filled prefix blocks and roll back the rejected tail's
        surplus blocks."""
        import jax.numpy as jnp
        Q = self._spec_k + 1
        M = self.config.max_slots
        toks = np.zeros((M, Q), np.int32)
        dl = np.zeros((M,), np.int32)
        for req in decoding:
            m = req.slot
            d = drafts.get(req.rid, [])
            toks[m, 0] = self._tokens[m]
            toks[m, 1:1 + len(d)] = d
            toks[m, 1 + len(d):] = self._tokens[m]   # pad: a real token
            dl[m] = len(d)
        t0 = time.time()
        with _watchdog.section("serving.decode"):
            self.cache.pool, cand, acc = self._jspec(
                self._params, self.cache.pool, jnp.asarray(toks),
                jnp.asarray(self._seq_lens), jnp.asarray(dl),
                jnp.asarray(self._steps_left), jnp.asarray(self._done),
                jnp.asarray(self.cache.tables), jnp.asarray(self._keys),
                jnp.asarray(self._sample_idx), jnp.asarray(self._temp),
                jnp.asarray(self._topk), jnp.asarray(self._topp),
                *self._lora_operand(self._adapters))
            cand = np.asarray(cand)
            acc = np.asarray(acc)
        self._record_dispatch("spec", t0)
        for req in decoding:
            m = req.slot
            if self._done[m] or self._steps_left[m] <= 0:
                continue
            got = [int(t) for t in cand[m, :int(acc[m]) + 1]]
            eos = req.eos_token_id
            if eos is not None and eos in got:
                got = got[:got.index(eos) + 1]
                self._done[m] = True
                req.eos_seen = True
            e = len(got)
            req.tokens.extend(got)
            emitted.setdefault(req.rid, []).extend(got)
            req.spec_drafted += int(dl[m])
            req.spec_accepted += e - 1
            self._sched.spec_drafted += int(dl[m])
            self._sched.spec_accepted += e - 1
            self._tokens[m] = got[-1]
            self._seq_lens[m] += e
            self._steps_left[m] -= e
            self._sample_idx[m] = len(req.tokens)
            sl = int(self._seq_lens[m])
            base = req.reg_state[0] * self.config.block_size
            if self.config.prefix_cache and \
                    sl // self.config.block_size > req.reg_state[0]:
                req.reg_state = self.cache.register_prefix(
                    self._chain_ids(req, base, sl), req.blocks, sl,
                    req.reg_state, base=base, tenant=req.tenant,
                    namespace=req.adapter_id)
            if not req.finished:
                self._rollback_blocks(req)
        self._stats["spec_steps"] += 1

    # ---- mixed batching (ISSUE 20) ----------------------------------------

    def _mixed_dispatch(self, prefills: List[Request],
                        include_decode: bool,
                        emitted: Dict[int, List[int]]) -> None:
        """ONE mixed prefill+decode dispatch: every mid-prefill slot
        contributes its next chunk as a ``q_len > 1`` row (KV scattered
        from its per-row ``num_computed`` start), every decoding slot a
        ``q_len == 1`` row that samples its next token — per-row
        ``start``/``q_len`` are DEVICE operands of one executable per Q
        bucket, so role churn never retraces. A chunk that COMPLETES its
        prompt samples the first token in this same dispatch (TTFT no
        longer waits for the next step's decode); incomplete chunks and
        readmission recomputes discard their sampled lane. Block
        planning, preemption, prefix-cache registration, LoRA operands
        and journal cursors are exactly the two-phase path's — token
        streams are bit-identical either way."""
        import jax.numpy as jnp

        from ...models.generation import seed_key
        chunk = self.config.prefill_chunk
        M = self.config.max_slots
        bs = self.config.block_size
        decode_rows = [r for r in self._sched.decoding
                       if include_decode and not self._done[r.slot]
                       and self._steps_left[r.slot] > 0]
        plan: List[Tuple[Request, int]] = []
        qmax = 1
        for req in prefills:
            n = len(req.prefill_ids) - req.num_computed
            if chunk is not None:
                n = min(n, chunk)
            plan.append((req, n))
            qmax = max(qmax, n)
        Q = self._bucket(qmax)
        toks = np.zeros((M, Q), np.int32)
        starts = np.zeros((M,), np.int32)
        qlens = np.ones((M,), np.int32)           # pad rows: harmless q=1
        active = np.zeros((M,), bool)
        keys = np.zeros((M, 2), np.uint32)
        sidx = np.zeros((M,), np.int32)
        temp = np.zeros((M,), np.float32)
        topk = np.zeros((M,), np.int32)
        topp = np.ones((M,), np.float32)
        adapters = np.array(self._adapters)
        for r in decode_rows:
            m = r.slot
            toks[m, :] = self._tokens[m]          # pad lanes: a real token
            starts[m] = self._seq_lens[m]
            active[m] = True
            keys[m] = self._keys[m]
            sidx[m] = self._sample_idx[m]
            temp[m] = self._temp[m]
            topk[m] = self._topk[m]
            topp[m] = self._topp[m]
        for req, n in plan:
            m = req.slot
            ids = req.prefill_ids[req.num_computed:req.num_computed + n]
            toks[m, :n] = ids
            toks[m, n:] = ids[-1]                 # pad lanes: a real token
            starts[m] = req.num_computed
            qlens[m] = n
            active[m] = True
            # the completing chunk's sampled lane IS the prompt's first
            # token: the same (seed, index 0) key _first_tokens uses
            keys[m] = seed_key(req.seed)
            sidx[m] = 0
            temp[m] = req.temperature
            topk[m] = req.top_k if req.top_k is not None else 0
            topp[m] = req.top_p if req.top_p is not None else 1.0
            adapters[m] = req.adapter_slot
        t0 = time.time()
        with _watchdog.section("serving.decode"):
            self.cache.pool, nxt = self._jmixed(
                self._params, self.cache.pool, jnp.asarray(toks),
                jnp.asarray(starts), jnp.asarray(qlens),
                jnp.asarray(active), jnp.asarray(self.cache.tables),
                jnp.asarray(keys), jnp.asarray(sidx), jnp.asarray(temp),
                jnp.asarray(topk), jnp.asarray(topp),
                *self._lora_operand(adapters))
            nxt = np.asarray(nxt)
        self._record_dispatch("mixed", t0)
        now = time.time()
        # prefill rows first (the two-phase path's bookkeeping order:
        # _advance_prefills before the decode dispatch's commits)
        for req, n in plan:
            m = req.slot
            req.num_computed += n
            req.reg_state = self.cache.register_prefix(
                req.prefill_ids, req.blocks, req.num_computed,
                req.reg_state, tenant=req.tenant,
                namespace=req.adapter_id)
            if req.prefilling:
                continue                          # more chunks to go
            if req.tokens:                        # readmission: resume
                self._start_decode(req)
            else:
                self._emit_first(req, int(nxt[m]), now, emitted)
        # decode rows: exactly one iteration of the decode loop's commit
        for req in decode_rows:
            m = req.slot
            t = int(nxt[m])
            req.tokens.append(t)
            emitted.setdefault(req.rid, []).append(t)
            self._tokens[m] = t
            self._seq_lens[m] += 1
            self._steps_left[m] -= 1
            self._sample_idx[m] = len(req.tokens)
            if req.eos_token_id is not None and t == req.eos_token_id:
                self._done[m] = True
                req.eos_seen = True
            sl = int(self._seq_lens[m])
            base = req.reg_state[0] * bs
            if self.config.prefix_cache and sl // bs > req.reg_state[0]:
                req.reg_state = self.cache.register_prefix(
                    self._chain_ids(req, base, sl), req.blocks, sl,
                    req.reg_state, base=base, tenant=req.tenant,
                    namespace=req.adapter_id)

    # ---- the scheduler iteration ------------------------------------------

    def step(self, max_iters: Optional[int] = None) -> Dict[int, List[int]]:
        """One scheduler iteration: expire deadlines -> retire -> admit
        (+ prefill) -> advance chunked prefills -> extend/preempt for
        blocks -> one decode dispatch of up to ``_limit()`` iterations
        (``max_iters`` caps it). Returns ``{rid: [tokens emitted]}``.
        Each step stamps the global :mod:`~paddle_tpu.health.watchdog`
        (progress tick + ``serving.step``/``serving.prefill``/
        ``serving.decode`` section markers), so a frozen dispatch is
        named in the hang diagnosis exactly like a training section."""
        _watchdog.touch()
        with self._lock, _watchdog.section("serving.step"):
            emitted = self._step(max_iters)
            self._lora_sweep()
            self._journal_step(emitted)
            return emitted

    def _step(self, max_iters: Optional[int]) -> Dict[int, List[int]]:
        import jax.numpy as jnp
        emitted: Dict[int, List[int]] = {}
        self._expire_deadlines(time.time())
        self._sched.retire_finished()
        self._admit(emitted)
        if not self.config.mixed_batch:
            # two-phase path (the parity oracle): one B=1 chunk dispatch
            # per mid-prefill slot BEFORE the decode dispatch, which
            # _limit then clamps at decode_chunk while any prompt is
            # mid-prefill. In mixed mode the chunks ride the mixed
            # dispatch below instead, so the clamp never engages.
            self._advance_prefills(emitted)
        k = 0
        decoding = self._sched.decoding
        if decoding and self._spec_k:
            # speculative path: draft by prompt lookup; with at least one
            # draft the step runs ONE multi-query verify dispatch instead
            # of the decode loop (draft-less slots ride it as a plain
            # single step). No draft anywhere — none found, or the block
            # planner DROPPED them under pool pressure — falls through to
            # the decode loop: a verify with all-pad lanes would pay
            # ~Q x the FLOPs of a decode iteration to emit one token per
            # slot, while the loop batches many iterations per dispatch.
            drafts = {r.rid: self._draft_tokens(r) for r in decoding}
            if any(drafts.values()):
                decoding = self._ensure_blocks_spec(drafts)
                if decoding and any(drafts.values()):
                    self._spec_dispatch(decoding, drafts, emitted)
                    self._sched.retire_finished()
                    self._stats["steps"] += 1
                    return emitted
            decoding = self._sched.decoding
        if self.config.mixed_batch and \
                any(r.prefilling for r in self._sched.live):
            # mixed batching (ISSUE 20): every mid-prefill slot's chunk
            # rides the decode dispatch as a q_len > 1 row of ONE mixed
            # step — no per-prompt B=1 chunk dispatches, no decode_chunk
            # clamp, and decoding slots advance in the SAME step a new
            # prompt prefills. Precedence: a step with spec drafts
            # dispatched verify above and never reaches here. Block
            # planning is the decode planner's (_ensure_blocks for the
            # decode rows' one iteration; a preemption inside it may
            # shrink either role set, so both are re-read after).
            kd = self._ensure_blocks(1) if decoding else 0
            prefills = [r for r in self._sched.live if r.prefilling]
            if prefills:
                self._mixed_dispatch(prefills, kd >= 1, emitted)
                self._sched.retire_finished()
                self._stats["steps"] += 1
                return emitted
            decoding = self._sched.decoding
        if decoding:
            want = self._limit(decoding, max_iters)
            k = self._ensure_blocks(want)
            decoding = self._sched.decoding       # preemption may shrink it
            if decoding and k >= 1:
                # an in-call preemption re-queued its victim, flipping the
                # sizing policy from drain-the-tail to first-retirement;
                # re-derive the cap so the victim isn't stalled for the
                # survivors' whole remaining budget (no-op otherwise)
                k = min(k, self._limit(decoding, max_iters))
        if decoding and k >= 1:
            before = self._steps_left.copy()
            t0 = time.time()
            with _watchdog.section("serving.decode"):
                (self.cache.pool, tokens, seq_lens, steps_left, done,
                 toks) = self._jdecode(
                    self._params, self.cache.pool, jnp.asarray(self._tokens),
                    jnp.asarray(self._seq_lens),
                    jnp.asarray(self._steps_left),
                    jnp.asarray(self._done), jnp.asarray(self.cache.tables),
                    jnp.asarray(self._eos), jnp.asarray(k, jnp.int32),
                    jnp.asarray(self._keys), jnp.asarray(self._sample_idx),
                    jnp.asarray(self._temp), jnp.asarray(self._topk),
                    jnp.asarray(self._topp),
                    *self._lora_operand(self._adapters))
                toks = np.asarray(toks)
            self._record_dispatch("decode", t0)
            # np.array (copy): zero-copy views of jax outputs are read-only,
            # and admission writes these slots in place next step
            self._tokens = np.array(tokens)
            self._seq_lens = np.array(seq_lens)
            self._steps_left = np.array(steps_left)
            self._done = np.array(done)
            for req in decoding:
                m = req.slot
                n = int(before[m] - self._steps_left[m])
                if n <= 0:
                    continue
                got = toks[m, :n].tolist()
                req.tokens.extend(got)
                self._sample_idx[m] = len(req.tokens)
                if bool(self._done[m]):
                    req.eos_seen = True
                emitted.setdefault(req.rid, []).extend(got)
                # blocks the dispatch just completed become shareable;
                # skip the chain-ids build unless a block actually filled
                # (reg_state makes registration itself incremental)
                sl = int(self._seq_lens[m])
                base = req.reg_state[0] * self.config.block_size
                if self.config.prefix_cache and \
                        sl // self.config.block_size > req.reg_state[0]:
                    req.reg_state = self.cache.register_prefix(
                        self._chain_ids(req, base, sl), req.blocks, sl,
                        req.reg_state, base=base, tenant=req.tenant,
                        namespace=req.adapter_id)
            self._sched.retire_finished()
        self._stats["steps"] += 1
        return emitted

    def stream(self, finish_events: bool = False
               ) -> Iterator[Tuple[int, Any]]:
        """Drain the engine, yielding ``(rid, token)`` events in emission
        order (within a step, by request id). Dispatches are capped at
        ``decode_chunk`` iterations so events surface with bounded latency
        instead of arriving in one tail-drain burst. With
        ``finish_events=True``, each request's retirement additionally
        yields ``(rid, dict)`` carrying its serving record —
        ``prefix_hit_tokens`` / ``preemptions`` / ``recomputed_tokens`` /
        ``tokens`` / ``ttft_s`` — so a streaming caller observes the
        paging machinery per request, not just in aggregate stats().

        Consumer abandonment: closing the generator (``gen.close()``, a
        ``break`` followed by GC, the SSE client vanishing) CANCELS every
        request still queued or running — their KV blocks return to the
        pool immediately instead of leaking until someone else drains the
        engine. The partial outputs stay readable via :meth:`request`."""
        try:
            while self.pending:
                seen = set(self._sched.finished) if finish_events else None
                for rid, toks in sorted(
                        self.step(self.config.decode_chunk).items()):
                    for t in toks:
                        yield rid, int(t)
                if finish_events:
                    for rid in sorted(r for r in self._sched.finished
                                      if r not in seen):
                        req = self._sched.finished[rid]
                        yield rid, {
                            "finished": True,
                            "state": req.state,
                            "tokens": len(req.tokens),
                            "prefix_hit_tokens": req.prefix_hit_tokens,
                            "preemptions": req.preemptions,
                            "recomputed_tokens": req.recomputed_tokens,
                            "oom_truncated": req.oom_truncated,
                            "ttft_s": req.ttft_s,
                        }
        except GeneratorExit:
            # the consumer walked away mid-stream: nobody will ever pump
            # step() for these requests again through this generator —
            # cancel them so their blocks can't sit pinned in the pool
            self.cancel_all()
            raise

    def run(self, prompts: Sequence, max_new_tokens=None,
            eos_token_id="unset") -> List[np.ndarray]:
        """Submit every prompt, drain, return outputs in submission order.
        ``max_new_tokens`` may be one int or a per-prompt sequence."""
        n = len(prompts)
        mnt = ([max_new_tokens] * n
               if max_new_tokens is None or np.isscalar(max_new_tokens)
               else list(max_new_tokens))
        if len(mnt) != n:
            raise ValueError(f"max_new_tokens has {len(mnt)} entries for "
                             f"{n} prompts")
        rids = [self.submit(p, max_new_tokens=m, eos_token_id=eos_token_id)
                for p, m in zip(prompts, mnt)]
        while self.pending:
            self.step()
        return [self._sched.result(r) for r in rids]

    # ---- introspection ----------------------------------------------------

    @property
    def pending(self) -> bool:
        return self._sched.pending

    def depth(self) -> int:
        """Queued + live request count under the engine lock — the
        router-visible load signal its power-of-two-choices pick
        compares (cheaper than a full health_snapshot per submit)."""
        with self._lock:
            return self._sched.depth

    def request(self, rid: int) -> Request:
        """The finished request record (tokens + latency timestamps +
        prefix-hit/preemption counters)."""
        with self._lock:
            return self._sched.finished[rid]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> Dict[str, Any]:
        return {**self._stats,
                "prefill_buckets": len(self._prefill_buckets),
                "admitted": self._sched.admitted,
                "retired": self._sched.retired,
                "cancelled": self._sched.cancelled,
                "timed_out": self._sched.timed_out,
                "shed": self._sched.shed,
                "queued": len(self._sched.queue),
                "live_slots": len(self._sched.live),
                "max_slots": self.config.max_slots,
                "policy": self._policy.name,
                "free_blocks": self.cache.free_blocks,
                "prefix_hit_tokens": self._sched.prefix_hit_tokens,
                "preemptions": self._sched.preemptions,
                "recomputed_tokens": self._sched.recomputed_tokens,
                "oom_truncated": self._sched.oom_truncated,
                "cached_blocks": self.cache.manager.cached_blocks,
                "evictions": self.cache.manager.evictions,
                "usable_blocks": self.cache.manager.num_blocks - 1,
                "kv_quant": self.config.kv_quant,
                "paged_kernel": self.config.paged_kernel,
                "spec_decode": self.config.spec_decode,
                "spec_drafted": self._sched.spec_drafted,
                "spec_accepted": self._sched.spec_accepted,
                "tp_degree": self.config.tp,
                "kv_pool_bytes": self.cache.kv_bytes(),
                "kv_pool_shard_bytes": self.cache.kv_bytes(per_shard=True),
                "kv_pool_mb": round(self.cache.kv_bytes() / 2**20, 2),
                "dispatch_latency": self._dispatch_latency(),
                "offload": (self.cache.offload.stats()
                            if self.cache.offload is not None else None),
                "lora": (self._lora.stats()
                         if self._lora is not None else None)}

    def health_snapshot(self) -> Dict[str, Any]:
        """One JSON-serializable health/ops record (docs/OPS.md): overall
        readiness, capacity headroom, lifecycle/shed counters, hang-
        watchdog state and per-tenant queue-depth/TTFT/shed breakdowns —
        the payload a ``/healthz`` or metrics endpoint should serve.
        ``ok`` goes False only when the installed hang watchdog has fired
        (the engine itself degrades by shedding, which is healthy);
        ``accepting`` says whether a submit() right now would be queued
        rather than shed. Safe to call from any thread — the whole
        payload is built under the engine lock, so a metrics endpoint
        polling mid-trace never sees a torn mid-step state."""
        with self._lock:
            return self._health_snapshot_locked()

    def block_partition(self) -> Dict[str, int]:
        """A consistent view of the pool partition (free / evictable /
        in-use / usable) under the engine lock — the conservation
        invariant the InvariantAuditor (audit.py) checks every step:
        free + evictable + in_use == usable. With the host offload tier
        attached, ``host``/``host_capacity`` report the host-resident
        side of the two-tier partition (the auditor's ``tier_partition``
        check: a key is device-resident XOR host-resident)."""
        with self._lock:
            bm = self.cache.manager
            tier = self.cache.offload
            return {"free": len(bm._free),
                    "evictable": len(bm._evictable),
                    "in_use": bm.blocks_in_use,
                    "usable": bm.num_blocks - 1,
                    "host": tier.blocks if tier is not None else 0,
                    "host_capacity": tier.capacity
                    if tier is not None else 0}

    def _health_snapshot_locked(self) -> Dict[str, Any]:
        sched = self._sched
        wd = _watchdog.current()

        def pct(xs, q):
            return (round(float(np.percentile(np.asarray(xs), q)), 4)
                    if xs else None)

        # tenants past MAX_TENANTS were folded into the overflow record
        # at submit; by_tenant() folds queued/live the same way (or the
        # overflow row would report 0 forever)
        occupancy = sched.by_tenant()
        tenants = {}
        for name, t in sched.tenants.items():
            ttfts = list(t["ttfts"])
            tpots = list(t["tpots"])
            tenants[name] = {
                "queued": occupancy[name]["queued"],
                "live": occupancy[name]["live"],
                "submitted": t["submitted"], "admitted": t["admitted"],
                "retired": t["retired"], "cancelled": t["cancelled"],
                "timed_out": t["timed_out"], "shed": t["shed"],
                "service_tokens": t["service_tokens"],
                "cached_blocks": self.cache.manager.tenant_cached(name),
                "ttft_p50_s": pct(ttfts, 50), "ttft_p99_s": pct(ttfts, 99),
                # TPOT (time per output token): each retirement's mean
                # inter-token decode latency is one sample, so the
                # percentiles track the SLO a streaming client feels
                "tpot_p50_s": pct(tpots, 50), "tpot_p99_s": pct(tpots, 99),
            }
        return {
            "ok": wd is None or not wd.fired.is_set(),
            "accepting": len(sched.queue) < sched.queue_depth,
            "policy": self._policy.name,
            "queued": len(sched.queue),
            "queue_limit": sched.queue_depth,
            "live_slots": len(sched.live),
            "max_slots": self.config.max_slots,
            "free_blocks": self.cache.free_blocks,
            "usable_blocks": self.cache.manager.num_blocks - 1,
            "kv_pool_bytes": self.cache.kv_bytes(),
            "tp_degree": self.config.tp,
            "kv_pool_shard_bytes": self.cache.kv_bytes(per_shard=True),
            "kv_quant": self.config.kv_quant,
            "paged_kernel": self.config.paged_kernel,
            "spec_decode": self.config.spec_decode,
            "retry_after_s": sched.retry_after_s(),
            "counters": {
                "admitted": sched.admitted, "retired": sched.retired,
                "cancelled": sched.cancelled, "timed_out": sched.timed_out,
                "shed": sched.shed, "preemptions": sched.preemptions,
                "oom_truncated": sched.oom_truncated,
                "prefix_hit_tokens": sched.prefix_hit_tokens,
                "evictions": self.cache.manager.evictions,
            },
            "dispatch_latency": self._dispatch_latency(),
            "offload": {
                "enabled": self.cache.offload is not None,
                **(self.cache.offload.stats()
                   if self.cache.offload is not None else
                   {"capacity": 0, "blocks": 0, "swap_outs": 0,
                    "swap_ins": 0, "tier_hits": 0, "tier_misses": 0,
                    "corrupt_drops": 0, "tier_evictions": 0}),
            },
            "lora": {
                "enabled": self._lora is not None,
                **(self._lora.snapshot() if self._lora is not None else
                   {"rank": 0, "slots": 0, "resident": [],
                    "adapters_registered": 0, "adapters_resident": 0,
                    "adapter_loads": 0, "adapter_evictions": 0,
                    "adapter_pins": 0}),
            },
            "watchdog": {
                "installed": wd is not None,
                "fired": bool(wd.fired.is_set()) if wd is not None else False,
                "timeout_s": wd.timeout if wd is not None else None,
            },
            "tenants": tenants,
        }
