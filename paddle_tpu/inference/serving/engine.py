"""Continuous-batching serving engine over the paged KV cache.

The serving tier the ROADMAP's "heavy traffic" north star asks for:
iteration-level scheduling (Orca) + a paged KV cache (PagedAttention) on
top of the compiled decode path PR 2 built (donated buffers, one program
per shape).

Design (docs/SERVING.md):

* **One compiled decode program.** The decode step runs over a FIXED
  ``max_slots``-wide slot table — shapes never change, so it traces once
  and the per-iteration host cost is one dispatch. The iteration bound is
  a DEVICE SCALAR argument (no retrace): with work queued the dispatch
  returns exactly when the first live slot exhausts its budget, so
  retirement/admission happen with zero idle iterations; with the queue
  empty one dispatch drains the whole tail. ``decode_chunk`` caps the
  bound only when a live slot can retire EARLY (EOS enabled) or the
  caller streams (token-granularity responsiveness).
* **Paged KV.** Slots attend through per-slot block tables into one
  physical block pool (``models.generation.paged_decode_step``); a retired
  slot's blocks return to the pool immediately and the next queued request
  reuses them.
* **Bucketed prefill.** Admission prefills at the prompt's power-of-2
  bucket length with the batch dim padded to the power-of-2 bucket of the
  ADMISSION-WAVE size (not always ``max_slots`` — most waves admit one
  request and pay one row of flops), so prefill executables are bounded by
  ``len_buckets * batch_buckets``, not by distinct prompt lengths or wave
  sizes.
* **Greedy (v1).** The engine samples by argmax on device; temperature /
  top-k/top-p serving stays on the batch ``generate()`` tier. int8
  weight-only decode rides transparently via ``quantize="int8"``
  (``llama.quantize_params`` — `_mm` routes every projection through the
  stream-dequant path).

API::

    engine = ServingEngine(params, model_cfg, ServingConfig(max_slots=8))
    rid = engine.submit(prompt_ids, max_new_tokens=64)
    while engine.pending:
        for rid, toks in engine.step().items(): ...
    # or: for rid, tok in engine.stream(): ...
    # or: outs = engine.run(prompts, max_new_tokens=64)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ...flags import flag
from .paged_cache import PagedKVCache
from .scheduler import Request, Scheduler, ServingQueueFull  # noqa: F401

__all__ = ["ServingConfig", "ServingEngine"]


@dataclasses.dataclass
class ServingConfig:
    """Engine shape/capacity knobs. ``None`` fields resolve from the
    ``FLAGS_serving_*`` registry at construction (flags.py), so a fleet can
    retune the engine from the environment without code changes."""

    block_size: Optional[int] = None
    max_slots: Optional[int] = None
    max_model_len: Optional[int] = None
    queue_depth: Optional[int] = None
    decode_chunk: Optional[int] = None
    num_blocks: int = 0              # 0 = auto (max_slots full sequences)
    quantize: Optional[str] = None   # "int8" -> weight-only decode path
    cache_dtype: Any = None          # None -> model activation dtype

    def __post_init__(self):
        for f, name in (("block_size", "FLAGS_serving_block_size"),
                        ("max_slots", "FLAGS_serving_max_slots"),
                        ("max_model_len", "FLAGS_serving_max_model_len"),
                        ("queue_depth", "FLAGS_serving_queue_depth"),
                        ("decode_chunk", "FLAGS_serving_decode_chunk")):
            if getattr(self, f) is None:
                setattr(self, f, int(flag(name)))
        from ...models.llama import QUANTIZE_MODES
        if self.quantize not in QUANTIZE_MODES:
            raise ValueError(f"unknown quantize mode {self.quantize!r}; "
                             f"options: {QUANTIZE_MODES}")


class ServingEngine:
    """Continuous-batching greedy decode service over a causal-LM pytree."""

    def __init__(self, params, model_config, serving_config:
                 Optional[ServingConfig] = None, gen_config=None):
        import jax

        from ...models.generation import GenerationConfig
        self.config = serving_config or ServingConfig()
        self._gen = gen_config or GenerationConfig()
        if self._gen.temperature:
            raise ValueError(
                "ServingEngine is greedy-only (temperature=0); sampling "
                "serving stays on GenerationPredictor.generate")
        from ...models.llama import ensure_quantized
        self._params = ensure_quantized(params, self.config.quantize)
        self._cfg = model_config
        self.cache = PagedKVCache(model_config, self.config.max_slots,
                                  self.config.max_model_len,
                                  self.config.block_size,
                                  self.config.num_blocks,
                                  dtype=self.config.cache_dtype)
        self._sched = Scheduler(self.cache, self.config.max_slots,
                                self.config.queue_depth)
        M = self.config.max_slots
        self._tokens = np.zeros((M,), np.int32)
        self._seq_lens = np.zeros((M,), np.int32)
        self._steps_left = np.zeros((M,), np.int32)
        self._done = np.ones((M,), bool)          # empty slots are inactive
        self._eos = np.full((M,), -1, np.int32)
        self._stats = {"decode_traces": 0, "prefill_traces": 0,
                       "chunks": 0, "steps": 0}
        self._prefill_buckets: set = set()
        # widest token buffer one dispatch can emit per slot (a budget
        # never exceeds max_model_len KV entries, so neither can steps)
        self._out_width = int(self.config.max_model_len)
        self._jax = jax
        self._jprefill, self._jdecode = self._build(jax)

    # ---- compiled programs ------------------------------------------------

    def _build(self, jax):
        import jax.numpy as jnp
        from jax import lax

        from ...jit.train_step import donation_supported
        from ...models import generation as G
        cfg, stats, Cmax = self._cfg, self._stats, self._out_width

        def prefill_fn(params, ids, prompt_lens, block_tables, pool, active):
            stats["prefill_traces"] += 1           # trace-time only
            return G.paged_prefill(params, cfg, ids, prompt_lens,
                                   block_tables, pool, active)

        def decode_fn(params, pool, tokens, seq_lens, steps_left, done,
                      block_tables, eos_ids, limit):
            stats["decode_traces"] += 1            # trace-time only
            M = tokens.shape[0]

            # while (not scan): the chunk EXITS the moment every live row
            # is done, so a retirement wave mid-chunk costs nothing — the
            # same alive-mask early exit the batch generate() loop uses.
            # ``limit`` is a device scalar, so the host can size every
            # dispatch to the schedule (return at the next budget
            # retirement; drain the tail in one go) without retracing
            def body(carry):
                i, tokens, seq_lens, steps_left, done, pool, out = carry
                active = (~done) & (steps_left > 0)
                logits, pool, _drops = G.paged_decode_step(
                    params, cfg, tokens, seq_lens, block_tables, pool,
                    active)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                nxt = jnp.where(active, nxt, tokens)
                done = done | (active & (nxt == eos_ids))
                seq_lens = seq_lens + active
                steps_left = steps_left - active.astype(jnp.int32)
                out = lax.dynamic_update_slice(out, nxt[:, None], (0, i))
                return (i + 1, nxt, seq_lens, steps_left, done, pool, out)

            def cond(carry):
                i, _, _, steps_left, done, _, _ = carry
                return (i < limit) & ((~done) & (steps_left > 0)).any()

            out0 = jnp.zeros((M, Cmax), jnp.int32)
            (_, tokens, seq_lens, steps_left, done, pool, out) = \
                lax.while_loop(cond, body, (jnp.int32(0), tokens, seq_lens,
                                            steps_left, done, pool, out0))
            return pool, tokens, seq_lens, steps_left, done, out

        donate = donation_supported()
        jpre = jax.jit(prefill_fn, donate_argnums=(4,) if donate else ())
        jdec = jax.jit(decode_fn, donate_argnums=(1,) if donate else ())
        return jpre, jdec

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    # ---- request lifecycle ------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = "unset") -> int:
        """Queue one prompt; returns the request id. ``eos_token_id``
        defaults to the engine's GenerationConfig (pass ``None`` explicitly
        to disable EOS for this request)."""
        g = self._gen
        req = Request(
            rid=-1, prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens if max_new_tokens is not None
                               else g.max_new_tokens),
            eos_token_id=(g.eos_token_id if eos_token_id == "unset"
                          else eos_token_id))
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        return self._sched.submit(req)

    def _admit(self, emitted: Dict[int, List[int]]) -> None:
        import jax.numpy as jnp
        admitted: List[Request] = []
        while (req := self._sched.next_admission()) is not None:
            admitted.append(req)
        if not admitted:
            return
        # one prefill dispatch per BUCKET, batch dim padded to the
        # power-of-2 bucket of the GROUP size (<= max_slots): executables
        # stay bounded by len_buckets * batch_buckets, a burst of
        # admissions costs O(buckets) dispatches, and the common
        # steady-state wave (ONE request refilling a retired slot) pays
        # one row of prefill flops instead of max_slots rows
        M = self.config.max_slots
        by_bucket: Dict[int, List[Request]] = {}
        for req in admitted:
            by_bucket.setdefault(self._bucket(req.prompt_len), []).append(req)
        for Sb, group in sorted(by_bucket.items()):
            self._prefill_buckets.add(Sb)
            Bb = 1
            while Bb < len(group):
                Bb *= 2
            Bb = min(Bb, M)
            ids = np.zeros((Bb, Sb), np.int32)
            plens = np.ones((Bb,), np.int32)      # pad rows: harmless len 1
            tables = np.zeros((Bb, self.cache.blocks_per_seq), np.int32)
            act = np.zeros((Bb,), bool)
            for r, req in enumerate(group):
                ids[r, :req.prompt_len] = req.prompt
                plens[r] = req.prompt_len
                tables[r] = self.cache.tables[req.slot]
                act[r] = True
            logits, self.cache.pool, _ = self._jprefill(
                self._params, jnp.asarray(ids), jnp.asarray(plens),
                jnp.asarray(tables), self.cache.pool, jnp.asarray(act))
            first = np.argmax(np.asarray(logits), axis=-1)
            now = time.time()
            for r, req in enumerate(group):
                tok0 = int(first[r])
                req.first_token_t = now
                req.tokens.append(tok0)
                emitted.setdefault(req.rid, []).append(tok0)
                if req.eos_token_id is not None and \
                        tok0 == req.eos_token_id:
                    req.eos_seen = True
                if req.finished:
                    self._sched.finish(req)
                    continue
                m = req.slot
                self._tokens[m] = tok0
                self._seq_lens[m] = req.prompt_len
                self._steps_left[m] = req.max_new_tokens - 1
                self._done[m] = False
                self._eos[m] = -1 if req.eos_token_id is None \
                    else req.eos_token_id

    def _limit(self, live, max_iters: Optional[int]) -> int:
        """Iterations for the next decode dispatch. Queue waiting: run to
        the FIRST budget retirement (admit with zero idle iterations).
        Queue empty: drain the whole tail in one dispatch (the in-graph
        alive-mask exit handles rows finishing early). ``decode_chunk``
        caps the bound only when a live row can retire EARLIER than its
        budget (EOS enabled) so admission latency stays bounded, or when
        the caller asked for streaming granularity via ``max_iters``."""
        sl = [int(self._steps_left[r.slot]) for r in live]
        n = min(sl) if self._sched.queue else max(sl)
        if max_iters is None and \
                any(r.eos_token_id is not None for r in live):
            max_iters = self.config.decode_chunk
        if max_iters is not None:
            n = min(n, int(max_iters))
        return max(1, min(n, self._out_width))

    def step(self, max_iters: Optional[int] = None) -> Dict[int, List[int]]:
        """One scheduler iteration: retire -> admit (+ prefill) -> one
        decode dispatch of up to ``_limit()`` iterations (``max_iters``
        caps it). Returns ``{rid: [tokens emitted]}``."""
        import jax.numpy as jnp
        emitted: Dict[int, List[int]] = {}
        self._sched.retire_finished()
        self._admit(emitted)
        live = self._sched.live
        if live:
            before = self._steps_left.copy()
            (self.cache.pool, tokens, seq_lens, steps_left, done,
             toks) = self._jdecode(
                self._params, self.cache.pool, jnp.asarray(self._tokens),
                jnp.asarray(self._seq_lens), jnp.asarray(self._steps_left),
                jnp.asarray(self._done), jnp.asarray(self.cache.tables),
                jnp.asarray(self._eos),
                jnp.asarray(self._limit(live, max_iters), jnp.int32))
            toks = np.asarray(toks)
            # np.array (copy): zero-copy views of jax outputs are read-only,
            # and admission writes these slots in place next step
            self._tokens = np.array(tokens)
            self._seq_lens = np.array(seq_lens)
            self._steps_left = np.array(steps_left)
            self._done = np.array(done)
            for req in live:
                m = req.slot
                n = int(before[m] - self._steps_left[m])
                if n <= 0:
                    continue
                got = toks[m, :n].tolist()
                req.tokens.extend(got)
                if bool(self._done[m]):
                    req.eos_seen = True
                emitted.setdefault(req.rid, []).extend(got)
            self._stats["chunks"] += 1
            self._sched.retire_finished()
        self._stats["steps"] += 1
        return emitted

    def stream(self) -> Iterator[Tuple[int, int]]:
        """Drain the engine, yielding ``(rid, token)`` events in emission
        order (within a step, by request id). Dispatches are capped at
        ``decode_chunk`` iterations so events surface with bounded
        latency instead of arriving in one tail-drain burst."""
        while self.pending:
            for rid, toks in sorted(
                    self.step(self.config.decode_chunk).items()):
                for t in toks:
                    yield rid, int(t)

    def run(self, prompts: Sequence, max_new_tokens=None,
            eos_token_id="unset") -> List[np.ndarray]:
        """Submit every prompt, drain, return outputs in submission order.
        ``max_new_tokens`` may be one int or a per-prompt sequence."""
        n = len(prompts)
        mnt = ([max_new_tokens] * n
               if max_new_tokens is None or np.isscalar(max_new_tokens)
               else list(max_new_tokens))
        if len(mnt) != n:
            raise ValueError(f"max_new_tokens has {len(mnt)} entries for "
                             f"{n} prompts")
        rids = [self.submit(p, max_new_tokens=m, eos_token_id=eos_token_id)
                for p, m in zip(prompts, mnt)]
        while self.pending:
            self.step()
        return [self._sched.result(r) for r in rids]

    # ---- introspection ----------------------------------------------------

    @property
    def pending(self) -> bool:
        return self._sched.pending

    def request(self, rid: int) -> Request:
        """The finished request record (tokens + latency timestamps)."""
        return self._sched.finished[rid]

    def stats(self) -> Dict[str, Any]:
        return {**self._stats,
                "prefill_buckets": len(self._prefill_buckets),
                "admitted": self._sched.admitted,
                "retired": self._sched.retired,
                "queued": len(self._sched.queue),
                "live_slots": len(self._sched.live),
                "max_slots": self.config.max_slots,
                "free_blocks": self.cache.free_blocks,
                "kv_pool_mb": round(self.cache.kv_bytes() / 2**20, 2)}
