"""Continuous-batching serving engine over the paged KV cache.

The serving tier the ROADMAP's "heavy traffic" north star asks for:
iteration-level scheduling (Orca) + a paged KV cache (PagedAttention) on
top of the compiled decode path PR 2 built (donated buffers, one program
per shape).

Design (docs/SERVING.md):

* **One compiled decode program.** The decode step runs over a FIXED
  ``max_slots``-wide slot table — shapes never change, so it traces once
  and the per-iteration host cost is one dispatch. The iteration bound is
  a DEVICE SCALAR argument (no retrace): with work queued the dispatch
  returns exactly when the first live slot exhausts its budget, so
  retirement/admission happen with zero idle iterations; with the queue
  empty one dispatch drains the whole tail. ``decode_chunk`` caps the
  bound only when a live slot can retire EARLY (EOS enabled), a prompt is
  mid-chunked-prefill, or the caller streams (token granularity).
* **On-demand paged KV + preemption.** A sequence holds only the blocks
  covering KV it has actually written: admission allocates the prompt's
  blocks (prefix-cache hits are MAPPED, not recomputed), decode extends
  block by block ahead of each dispatch. When the pool runs dry the
  newest-admitted running sequence is PREEMPTED — blocks freed, tokens
  kept, re-queued at the front for recompute-on-readmission (greedy
  recompute is bit-identical) — so worst-case ``max_new`` budgets are
  never pre-charged and effective concurrency tracks real usage.
  ``preempt=False`` restores the legacy reservation-at-admission mode.
* **Automatic prefix caching.** Full KV blocks are content-hashed (chained
  block-aligned token-id keys) into the ref-counted ``BlockManager`` table
  as prefill/decode completes them; admissions sharing a system-prompt /
  few-shot prefix map the cached blocks and prefill only their suffix.
  Refcount-0 blocks stay cached on an LRU list until allocation pressure
  evicts them. ``prefix_cache=False`` disables.
* **Chunked prefill.** Prompts longer than ``prefill_chunk`` prefill in
  fixed-size chunks (``models.generation.paged_prefill_chunk`` — offset
  and length are device scalars) interleaved with decode dispatches, so a
  long admission no longer freezes in-flight streams. Short cold prompts
  still take the BATCHED bucketed prefill: one dispatch per power-of-2
  length bucket with the batch dim padded to the power-of-2 bucket of the
  admission-wave size.
* **Greedy (v1).** The engine samples by argmax on device; temperature /
  top-k/top-p serving stays on the batch ``generate()`` tier. int8
  weight-only decode rides transparently via ``quantize="int8"``.

API::

    engine = ServingEngine(params, model_cfg, ServingConfig(max_slots=8))
    rid = engine.submit(prompt_ids, max_new_tokens=64)
    while engine.pending:
        for rid, toks in engine.step().items(): ...
    # or: for rid, tok in engine.stream(): ...
    # or: outs = engine.run(prompts, max_new_tokens=64)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ...flags import flag
from .paged_cache import PagedKVCache
from .scheduler import Request, Scheduler, ServingQueueFull  # noqa: F401

__all__ = ["ServingConfig", "ServingEngine"]

_UNSET = "unset"


@dataclasses.dataclass
class ServingConfig:
    """Engine shape/capacity knobs. ``None`` fields resolve from the
    ``FLAGS_serving_*`` registry at construction (flags.py), so a fleet can
    retune the engine from the environment without code changes.

    The three feature knobs use the ``"unset"`` sentinel instead (the same
    convention as ``GenerationConfig.resolve``): left unset they resolve
    from their flag; an EXPLICIT ``None`` (or ``False``/``0``) disables
    the feature even when the flag enables it — ``prefix_cache=None`` and
    ``prefill_chunk=None`` are real overrides, not "not given".
    """

    block_size: Optional[int] = None
    max_slots: Optional[int] = None
    max_model_len: Optional[int] = None
    queue_depth: Optional[int] = None
    decode_chunk: Optional[int] = None
    num_blocks: int = 0              # 0 = auto (max_slots full sequences)
    quantize: Optional[str] = None   # "int8" -> weight-only decode path
    cache_dtype: Any = None          # None -> model activation dtype
    prefix_cache: Any = _UNSET       # bool; None/False = off
    prefill_chunk: Any = _UNSET      # tokens/chunk; None/0 = whole prompt
    preempt: Any = _UNSET            # bool; None/False = legacy reservation

    def __post_init__(self):
        for f, name in (("block_size", "FLAGS_serving_block_size"),
                        ("max_slots", "FLAGS_serving_max_slots"),
                        ("max_model_len", "FLAGS_serving_max_model_len"),
                        ("queue_depth", "FLAGS_serving_queue_depth"),
                        ("decode_chunk", "FLAGS_serving_decode_chunk")):
            if getattr(self, f) is None:
                setattr(self, f, int(flag(name)))
        if self.prefix_cache == _UNSET:
            self.prefix_cache = bool(flag("FLAGS_serving_prefix_cache"))
        else:
            self.prefix_cache = bool(self.prefix_cache)
        if self.preempt == _UNSET:
            self.preempt = bool(flag("FLAGS_serving_preempt"))
        else:
            self.preempt = bool(self.preempt)
        if self.prefill_chunk == _UNSET:
            self.prefill_chunk = int(flag("FLAGS_serving_prefill_chunk"))
        self.prefill_chunk = (int(self.prefill_chunk)
                              if self.prefill_chunk else None)
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1 or None/0 "
                             f"(got {self.prefill_chunk})")
        from ...models.llama import QUANTIZE_MODES
        if self.quantize not in QUANTIZE_MODES:
            raise ValueError(f"unknown quantize mode {self.quantize!r}; "
                             f"options: {QUANTIZE_MODES}")


class ServingEngine:
    """Continuous-batching greedy decode service over a causal-LM pytree."""

    def __init__(self, params, model_config, serving_config:
                 Optional[ServingConfig] = None, gen_config=None):
        import jax

        from ...models.generation import GenerationConfig
        self.config = serving_config or ServingConfig()
        self._gen = gen_config or GenerationConfig()
        if self._gen.temperature:
            raise ValueError(
                "ServingEngine is greedy-only (temperature=0); sampling "
                "serving stays on GenerationPredictor.generate")
        from ...models.llama import ensure_quantized
        self._params = ensure_quantized(params, self.config.quantize)
        self._cfg = model_config
        self.cache = PagedKVCache(model_config, self.config.max_slots,
                                  self.config.max_model_len,
                                  self.config.block_size,
                                  self.config.num_blocks,
                                  dtype=self.config.cache_dtype,
                                  prefix_cache=self.config.prefix_cache)
        self._sched = Scheduler(self.cache, self.config.max_slots,
                                self.config.queue_depth,
                                preempt=self.config.preempt)
        M = self.config.max_slots
        self._tokens = np.zeros((M,), np.int32)
        self._seq_lens = np.zeros((M,), np.int32)
        self._steps_left = np.zeros((M,), np.int32)
        self._done = np.ones((M,), bool)          # empty slots are inactive
        self._eos = np.full((M,), -1, np.int32)
        self._stats = {"decode_traces": 0, "prefill_traces": 0,
                       "chunk_prefill_traces": 0, "chunks": 0, "steps": 0}
        self._prefill_buckets: set = set()
        # widest token buffer one dispatch can emit per slot (a budget
        # never exceeds max_model_len KV entries, so neither can steps)
        self._out_width = int(self.config.max_model_len)
        self._jax = jax
        self._jprefill, self._jchunk, self._jdecode = self._build(jax)

    # ---- compiled programs ------------------------------------------------

    def _build(self, jax):
        import jax.numpy as jnp
        from jax import lax

        from ...jit.train_step import donation_supported
        from ...models import generation as G
        cfg, stats, Cmax = self._cfg, self._stats, self._out_width

        def prefill_fn(params, ids, prompt_lens, block_tables, pool, active):
            stats["prefill_traces"] += 1           # trace-time only
            return G.paged_prefill(params, cfg, ids, prompt_lens,
                                   block_tables, pool, active)

        def chunk_fn(params, ids, start, chunk_len, block_tables, pool):
            stats["chunk_prefill_traces"] += 1     # trace-time only
            return G.paged_prefill_chunk(params, cfg, ids, start, chunk_len,
                                         block_tables, pool)

        def decode_fn(params, pool, tokens, seq_lens, steps_left, done,
                      block_tables, eos_ids, limit):
            stats["decode_traces"] += 1            # trace-time only
            M = tokens.shape[0]

            # while (not scan): the chunk EXITS the moment every live row
            # is done, so a retirement wave mid-chunk costs nothing — the
            # same alive-mask early exit the batch generate() loop uses.
            # ``limit`` is a device scalar, so the host can size every
            # dispatch to the schedule (return at the next budget
            # retirement; drain the tail in one go) without retracing
            def body(carry):
                i, tokens, seq_lens, steps_left, done, pool, out = carry
                active = (~done) & (steps_left > 0)
                logits, pool, _drops = G.paged_decode_step(
                    params, cfg, tokens, seq_lens, block_tables, pool,
                    active)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                nxt = jnp.where(active, nxt, tokens)
                done = done | (active & (nxt == eos_ids))
                seq_lens = seq_lens + active
                steps_left = steps_left - active.astype(jnp.int32)
                out = lax.dynamic_update_slice(out, nxt[:, None], (0, i))
                return (i + 1, nxt, seq_lens, steps_left, done, pool, out)

            def cond(carry):
                i, _, _, steps_left, done, _, _ = carry
                return (i < limit) & ((~done) & (steps_left > 0)).any()

            out0 = jnp.zeros((M, Cmax), jnp.int32)
            (_, tokens, seq_lens, steps_left, done, pool, out) = \
                lax.while_loop(cond, body, (jnp.int32(0), tokens, seq_lens,
                                            steps_left, done, pool, out0))
            return pool, tokens, seq_lens, steps_left, done, out

        donate = donation_supported()
        jpre = jax.jit(prefill_fn, donate_argnums=(4,) if donate else ())
        jchk = jax.jit(chunk_fn, donate_argnums=(5,) if donate else ())
        jdec = jax.jit(decode_fn, donate_argnums=(1,) if donate else ())
        return jpre, jchk, jdec

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    # ---- request lifecycle ------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = "unset") -> int:
        """Queue one prompt; returns the request id. ``eos_token_id``
        defaults to the engine's GenerationConfig (pass ``None`` explicitly
        to disable EOS for this request)."""
        g = self._gen
        req = Request(
            rid=-1, prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens if max_new_tokens is not None
                               else g.max_new_tokens),
            eos_token_id=(g.eos_token_id if eos_token_id == "unset"
                          else eos_token_id))
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        return self._sched.submit(req)

    def _chain_ids(self, req: Request, start: int, stop: int) -> np.ndarray:
        """Token ids backing the KV entries ``[start, stop)`` a running
        request has written (entry p < prompt_len holds prompt[p]'s KV,
        entry p >= prompt_len holds tokens[p - prompt_len]'s) — the
        prefix-cache registration chain. Sliced, not the whole history:
        rebuilding prompt+tokens per filled block would cost O(seq_len^2)
        per request in the continuous-batching hot loop."""
        pl = len(req.prompt)
        if stop <= pl:
            return req.prompt[start:stop]
        gen = np.asarray(req.tokens[max(0, start - pl):stop - pl], np.int32)
        if start >= pl:
            return gen
        return np.concatenate([req.prompt[start:], gen])

    def _start_decode(self, req: Request) -> None:
        """Move a request whose prefill just completed into the decode slot
        arrays. Fresh requests enter with their first sampled token already
        in ``tokens``; readmitted ones resume from their last token."""
        m = req.slot
        self._tokens[m] = req.tokens[-1]
        self._seq_lens[m] = req.prompt_len + len(req.tokens) - 1
        self._steps_left[m] = req.max_new_tokens - len(req.tokens)
        self._done[m] = False
        self._eos[m] = -1 if req.eos_token_id is None else req.eos_token_id

    def _emit_first(self, req: Request, tok0: int, now: float,
                    emitted: Dict[int, List[int]]) -> None:
        req.first_token_t = now
        req.tokens.append(tok0)
        emitted.setdefault(req.rid, []).append(tok0)
        if req.eos_token_id is not None and tok0 == req.eos_token_id:
            req.eos_seen = True
        if req.finished:
            self._sched.finish(req)
        else:
            self._start_decode(req)

    def _admit(self, emitted: Dict[int, List[int]]) -> None:
        import jax.numpy as jnp
        admitted: List[Request] = []
        while (req := self._sched.next_admission()) is not None:
            admitted.append(req)
        if not admitted:
            return
        # split the wave: COLD short prompts take the batched bucketed
        # prefill (one dispatch per power-of-2 length bucket, batch dim
        # padded to the wave-size bucket); prefix-cache hits (prefill
        # starts at an offset), long prompts (chunked), and readmissions
        # (recompute) go through the offset chunk path, one row at a time
        chunk = self.config.prefill_chunk
        fast = [r for r in admitted
                if r.num_computed == 0 and not r.tokens
                and (chunk is None or r.prompt_len <= chunk)]
        M = self.config.max_slots
        by_bucket: Dict[int, List[Request]] = {}
        for req in fast:
            by_bucket.setdefault(self._bucket(req.prompt_len), []).append(req)
        for Sb, group in sorted(by_bucket.items()):
            self._prefill_buckets.add(Sb)
            Bb = 1
            while Bb < len(group):
                Bb *= 2
            Bb = min(Bb, M)
            ids = np.zeros((Bb, Sb), np.int32)
            plens = np.ones((Bb,), np.int32)      # pad rows: harmless len 1
            tables = np.zeros((Bb, self.cache.blocks_per_seq), np.int32)
            act = np.zeros((Bb,), bool)
            for r, req in enumerate(group):
                ids[r, :req.prompt_len] = req.prompt
                plens[r] = req.prompt_len
                tables[r] = self.cache.tables[req.slot]
                act[r] = True
            logits, self.cache.pool, _ = self._jprefill(
                self._params, jnp.asarray(ids), jnp.asarray(plens),
                jnp.asarray(tables), self.cache.pool, jnp.asarray(act))
            first = np.argmax(np.asarray(logits), axis=-1)
            now = time.time()
            for r, req in enumerate(group):
                req.num_computed = req.prompt_len
                req.reg_state = self.cache.register_prefix(
                    req.prompt, req.blocks, req.prompt_len, req.reg_state)
                self._emit_first(req, int(first[r]), now, emitted)
        # chunked/offset admissions advance via _advance_prefills

    def _advance_prefills(self, emitted: Dict[int, List[int]]) -> None:
        """One prefill chunk per mid-prefill slot (offset path, B=1):
        long admissions make progress WITHOUT freezing the decode slots —
        the decode dispatch between chunks is what kills head-of-line
        pressure. Completing requests emit their first token (fresh) or
        resume from their kept tokens (post-preemption recompute)."""
        import jax.numpy as jnp
        chunk = self.config.prefill_chunk
        for req in [r for r in self._sched.live if r.prefilling]:
            total = len(req.prefill_ids)
            n = total - req.num_computed
            if chunk is not None:
                n = min(n, chunk)
            Sb = self._bucket(n)
            ids = np.zeros((1, Sb), np.int32)
            ids[0, :n] = req.prefill_ids[req.num_computed:
                                         req.num_computed + n]
            logits, self.cache.pool, _ = self._jchunk(
                self._params, jnp.asarray(ids),
                jnp.asarray(req.num_computed, jnp.int32),
                jnp.asarray(n, jnp.int32),
                jnp.asarray(self.cache.tables[req.slot][None]),
                self.cache.pool)
            req.num_computed += n
            req.reg_state = self.cache.register_prefix(
                req.prefill_ids, req.blocks, req.num_computed,
                req.reg_state)
            if req.prefilling:
                continue                          # more chunks to go
            if req.tokens:                        # readmission: resume
                self._start_decode(req)
            else:
                tok0 = int(np.argmax(np.asarray(logits)[0]))
                self._emit_first(req, tok0, time.time(), emitted)

    # ---- decode dispatch sizing -------------------------------------------

    def _limit(self, decoding, max_iters: Optional[int]) -> int:
        """Iterations for the next decode dispatch. Queue waiting or a
        prompt mid-chunked-prefill: run to the FIRST budget retirement
        (admit with zero idle iterations) and cap at ``decode_chunk`` so
        prefill chunks interleave. Queue empty: drain the whole tail in
        one dispatch (the in-graph alive-mask exit handles rows finishing
        early). ``decode_chunk`` also caps when a live row can retire
        EARLIER than its budget (EOS enabled) so admission latency stays
        bounded, or when the caller asked for streaming granularity via
        ``max_iters``."""
        sl = [int(self._steps_left[r.slot]) for r in decoding]
        prefilling = any(r.prefilling for r in self._sched.live)
        waiting = bool(self._sched.queue) or prefilling
        n = min(sl) if waiting else max(sl)
        if prefilling or (max_iters is None and
                          any(r.eos_token_id is not None
                              for r in decoding)):
            max_iters = min(max_iters or self.config.decode_chunk,
                            self.config.decode_chunk)
        if max_iters is not None:
            n = min(n, int(max_iters))
        return max(1, min(n, self._out_width))

    def _ensure_blocks(self, want: int) -> int:
        """Make the pool cover ``want`` decode iterations for every
        decoding slot — each needs blocks for ``seq_len + min(want,
        steps_left)`` KV entries. Returns the feasible iteration count
        (shrunk to what the pool can back), PREEMPTING the newest-admitted
        live request (never the oldest — that's the no-livelock proof)
        whenever even one iteration doesn't fit. If the sole survivor
        still can't get a block the pool is truly exhausted relative to
        its budget: it is retired early with ``oom_truncated`` set rather
        than hung."""
        bf = self.cache.manager.blocks_for

        while True:
            decoding = self._sched.decoding
            if not decoding:
                return 0

            def need(k: int) -> int:
                tot = 0
                for r in decoding:
                    e = int(self._seq_lens[r.slot]) + \
                        min(k, int(self._steps_left[r.slot]))
                    tot += max(0, bf(e) - len(r.blocks))
                return tot

            avail = self.cache.free_blocks
            if need(1) <= avail:
                lo, hi = 1, max(1, want)
                while lo < hi:                    # largest feasible k
                    mid = (lo + hi + 1) // 2
                    if need(mid) <= avail:
                        lo = mid
                    else:
                        hi = mid - 1
                for r in decoding:
                    e = int(self._seq_lens[r.slot]) + \
                        min(lo, int(self._steps_left[r.slot]))
                    if self.cache.extend(r.slot, r.blocks, e) is None:
                        break                     # raced an estimate; retry
                else:
                    return lo
                continue
            victim = self._sched.preempt_victim()
            if victim is not None:
                self._preempt(victim)
                continue
            # sole oldest request and the pool STILL can't cover one more
            # block: its budget exceeds the whole pool. Truncate — retire
            # with the tokens it has — instead of hanging the drain loop.
            r = decoding[0]
            r.oom_truncated = True
            self._sched.oom_truncated += 1
            self._done[r.slot] = True
            return 0

    def _preempt(self, req: Request) -> None:
        m = req.slot
        self._sched.preempt(req)
        self._tokens[m] = 0
        self._seq_lens[m] = 0
        self._steps_left[m] = 0
        self._done[m] = True
        self._eos[m] = -1

    # ---- the scheduler iteration ------------------------------------------

    def step(self, max_iters: Optional[int] = None) -> Dict[int, List[int]]:
        """One scheduler iteration: retire -> admit (+ prefill) -> advance
        chunked prefills -> extend/preempt for blocks -> one decode
        dispatch of up to ``_limit()`` iterations (``max_iters`` caps it).
        Returns ``{rid: [tokens emitted]}``."""
        import jax.numpy as jnp
        emitted: Dict[int, List[int]] = {}
        self._sched.retire_finished()
        self._admit(emitted)
        self._advance_prefills(emitted)
        k = 0
        decoding = self._sched.decoding
        if decoding:
            want = self._limit(decoding, max_iters)
            k = self._ensure_blocks(want)
            decoding = self._sched.decoding       # preemption may shrink it
            if decoding and k >= 1:
                # an in-call preemption re-queued its victim, flipping the
                # sizing policy from drain-the-tail to first-retirement;
                # re-derive the cap so the victim isn't stalled for the
                # survivors' whole remaining budget (no-op otherwise)
                k = min(k, self._limit(decoding, max_iters))
        if decoding and k >= 1:
            before = self._steps_left.copy()
            (self.cache.pool, tokens, seq_lens, steps_left, done,
             toks) = self._jdecode(
                self._params, self.cache.pool, jnp.asarray(self._tokens),
                jnp.asarray(self._seq_lens), jnp.asarray(self._steps_left),
                jnp.asarray(self._done), jnp.asarray(self.cache.tables),
                jnp.asarray(self._eos), jnp.asarray(k, jnp.int32))
            toks = np.asarray(toks)
            # np.array (copy): zero-copy views of jax outputs are read-only,
            # and admission writes these slots in place next step
            self._tokens = np.array(tokens)
            self._seq_lens = np.array(seq_lens)
            self._steps_left = np.array(steps_left)
            self._done = np.array(done)
            for req in decoding:
                m = req.slot
                n = int(before[m] - self._steps_left[m])
                if n <= 0:
                    continue
                got = toks[m, :n].tolist()
                req.tokens.extend(got)
                if bool(self._done[m]):
                    req.eos_seen = True
                emitted.setdefault(req.rid, []).extend(got)
                # blocks the dispatch just completed become shareable;
                # skip the chain-ids build unless a block actually filled
                # (reg_state makes registration itself incremental)
                sl = int(self._seq_lens[m])
                base = req.reg_state[0] * self.config.block_size
                if self.config.prefix_cache and \
                        sl // self.config.block_size > req.reg_state[0]:
                    req.reg_state = self.cache.register_prefix(
                        self._chain_ids(req, base, sl), req.blocks, sl,
                        req.reg_state, base=base)
            self._stats["chunks"] += 1
            self._sched.retire_finished()
        self._stats["steps"] += 1
        return emitted

    def stream(self, finish_events: bool = False
               ) -> Iterator[Tuple[int, Any]]:
        """Drain the engine, yielding ``(rid, token)`` events in emission
        order (within a step, by request id). Dispatches are capped at
        ``decode_chunk`` iterations so events surface with bounded latency
        instead of arriving in one tail-drain burst. With
        ``finish_events=True``, each request's retirement additionally
        yields ``(rid, dict)`` carrying its serving record —
        ``prefix_hit_tokens`` / ``preemptions`` / ``recomputed_tokens`` /
        ``tokens`` / ``ttft_s`` — so a streaming caller observes the
        paging machinery per request, not just in aggregate stats()."""
        while self.pending:
            seen = set(self._sched.finished) if finish_events else None
            for rid, toks in sorted(
                    self.step(self.config.decode_chunk).items()):
                for t in toks:
                    yield rid, int(t)
            if finish_events:
                for rid in sorted(r for r in self._sched.finished
                                  if r not in seen):
                    req = self._sched.finished[rid]
                    yield rid, {
                        "finished": True,
                        "tokens": len(req.tokens),
                        "prefix_hit_tokens": req.prefix_hit_tokens,
                        "preemptions": req.preemptions,
                        "recomputed_tokens": req.recomputed_tokens,
                        "oom_truncated": req.oom_truncated,
                        "ttft_s": req.ttft_s,
                    }

    def run(self, prompts: Sequence, max_new_tokens=None,
            eos_token_id="unset") -> List[np.ndarray]:
        """Submit every prompt, drain, return outputs in submission order.
        ``max_new_tokens`` may be one int or a per-prompt sequence."""
        n = len(prompts)
        mnt = ([max_new_tokens] * n
               if max_new_tokens is None or np.isscalar(max_new_tokens)
               else list(max_new_tokens))
        if len(mnt) != n:
            raise ValueError(f"max_new_tokens has {len(mnt)} entries for "
                             f"{n} prompts")
        rids = [self.submit(p, max_new_tokens=m, eos_token_id=eos_token_id)
                for p, m in zip(prompts, mnt)]
        while self.pending:
            self.step()
        return [self._sched.result(r) for r in rids]

    # ---- introspection ----------------------------------------------------

    @property
    def pending(self) -> bool:
        return self._sched.pending

    def request(self, rid: int) -> Request:
        """The finished request record (tokens + latency timestamps +
        prefix-hit/preemption counters)."""
        return self._sched.finished[rid]

    def stats(self) -> Dict[str, Any]:
        return {**self._stats,
                "prefill_buckets": len(self._prefill_buckets),
                "admitted": self._sched.admitted,
                "retired": self._sched.retired,
                "queued": len(self._sched.queue),
                "live_slots": len(self._sched.live),
                "max_slots": self.config.max_slots,
                "free_blocks": self.cache.free_blocks,
                "prefix_hit_tokens": self._sched.prefix_hit_tokens,
                "preemptions": self._sched.preemptions,
                "recomputed_tokens": self._sched.recomputed_tokens,
                "oom_truncated": self._sched.oom_truncated,
                "cached_blocks": self.cache.manager.cached_blocks,
                "evictions": self.cache.manager.evictions,
                "kv_pool_mb": round(self.cache.kv_bytes() / 2**20, 2)}
