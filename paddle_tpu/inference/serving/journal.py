"""Crash-safe serving durability: write-ahead request journal + snapshots.

Every recovery path before this one (supervisor rebuild, cross-replica
failover, live migration, prefill handoff) lives inside one process — a
SIGKILL / host OOM / TPU-VM preemption of the serving process lost all
queued and in-flight requests. :class:`RequestJournal` closes that last
seam with the SAME durability contract PR 1 proved on the training side
(atomic tmp + fsync + rename, checksummed records, preemption-grace
emergency saves), specialized to the serving lifecycle:

* **Write-ahead log** (``journal.wal``): append-only records framed
  ``<u32 length><u32 crc32><payload>`` so a torn tail (process death
  mid-write, ``torn_journal_tail`` chaos) truncates cleanly at the last
  good frame instead of poisoning recovery. Three event kinds mirror the
  request lifecycle: ``submit`` (the FULL resolved record — prompt,
  budget, sampling knobs, tenant/priority/deadline/adapter — exactly what
  ``resubmit()`` needs), ``tok`` (the delivered-token cursor: the newly
  emitted token ids, logged under the engine lock at the step boundary
  that delivers them), and ``end`` (terminal transition: finished /
  cancelled / timed_out / shed / failed).
* **Fsync policy** (``FLAGS_serving_journal_sync``): ``step`` (default)
  batches ONE fsync per engine step — the same boundary at which tokens
  become visible to clients, so the journal never claims delivery of a
  token the caller could not have seen; ``always`` fsyncs every record
  (durable even mid-step, slowest); ``off`` leaves residency to the page
  cache (journal still survives process death, not host death).
* **Snapshots** (``snapshot-<seq>.snap``): periodically (every
  ``FLAGS_serving_snapshot_every`` flushes) the journal's in-memory
  mirror — {jid: record with delivered tokens + terminal state} — plus
  the fsynced WAL offset it covers is written tmp + fsync +
  ``os.replace`` with the same crc framing. Recovery loads the NEWEST
  snapshot that verifies (``corrupt_snapshot`` chaos degrades to the
  previous generation, then to a full WAL replay — never wrong state)
  and replays only the WAL suffix past its offset. The last two
  generations are kept.

KV blocks are deliberately NOT persisted: recovery recomputes them
through the existing bit-exact resubmit path (PR 11's invariant — token
``t`` is a pure function of (request, seed, t) — makes the recovered
stream identical), reusing whatever the prefix cache / host offload tier
still holds. What IS persisted is exactly the state that cannot be
recomputed: which requests exist, their resolved records, and how many
tokens each client has already been shown (the exactly-once ledger).

Ownership: a journal record belongs to at most one live engine request
(``Request.jid``). Deliberate same-fleet moves — migration, prefill
handoff, hedge resolution — transfer ownership (``resume``/``rebase``)
instead of terminating the record, so a cancel of the *vacated copy*
never marks the logical request dead. One :class:`RequestJournal` is
shared by every replica in a router fleet (jids are journal-global).
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ...flags import flag
from .scheduler import (CANCELLED, FINISHED, SHED, TIMED_OUT,
                        completes_by_tokens)

__all__ = ["JournalRecord", "RequestJournal", "LIVE", "SYNC_POLICIES"]

LIVE = "live"                       # non-terminal journal record state
_TERMINAL = frozenset({FINISHED, CANCELLED, TIMED_OUT, SHED, "failed"})
SYNC_POLICIES = ("step", "always", "off")

_FRAME = struct.Struct("<II")       # length, crc32(payload)
WAL_NAME = "journal.wal"
SNAPSHOT_PREFIX = "snapshot-"
SNAPSHOT_SUFFIX = ".snap"
KEEP_SNAPSHOTS = 2                  # generations retained on disk
KEEP_TERMINAL = 512                 # terminal records retained in the mirror


@dataclasses.dataclass
class JournalRecord:
    """The journal's mirror of one request: the resolved record (exactly
    the fields ``ServingEngine.resubmit`` needs), the delivered-token
    cursor, and the terminal state (``LIVE`` until an ``end`` event)."""

    jid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    tenant: str = "default"
    priority: int = 0
    deadline: Optional[float] = None
    adapter_id: Optional[str] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    state: str = LIVE

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    @property
    def finished_by_tokens(self) -> bool:
        """Delivered tokens alone complete the request — record it, don't
        re-run it (the ONE completion test recovery paths share)."""
        return completes_by_tokens(self.tokens, self.max_new_tokens,
                                   self.eos_token_id)

    def prompt_array(self) -> np.ndarray:
        return np.asarray(self.prompt, np.int32)

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "JournalRecord":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def _frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) \
        + payload


def _parse_frames(raw: bytes, offset: int = 0) -> Tuple[List[Dict], int]:
    """Parse framed JSON events from ``raw[offset:]``. Stops at the first
    incomplete or crc-mismatched frame (a torn tail). Returns the events
    and the byte offset just past the last GOOD frame."""
    events: List[Dict] = []
    pos = offset
    n = len(raw)
    while pos + _FRAME.size <= n:
        length, crc = _FRAME.unpack_from(raw, pos)
        start = pos + _FRAME.size
        end = start + length
        if end > n:
            break                                   # torn: frame cut short
        payload = raw[start:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break                                   # torn/corrupt payload
        try:
            events.append(json.loads(payload.decode("utf-8")))
        except (UnicodeDecodeError, json.JSONDecodeError):
            break
        pos = end
    return events, pos


class RequestJournal:
    """Append-only request journal + periodic serving-state snapshots.

    Thread-safe (own lock — a router fleet's replicas share one journal;
    each engine additionally serializes its own calls under the engine
    lock). All ``log_*`` appends go to a buffered file handle; ``flush()``
    is the once-per-engine-step durability point under the default
    ``step`` sync policy.
    """

    def __init__(self, journal_dir: str, sync: Optional[str] = None,
                 snapshot_every: Optional[int] = None):
        self.dir = str(journal_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.sync = str(sync if sync is not None
                        else flag("FLAGS_serving_journal_sync", "step"))
        if self.sync not in SYNC_POLICIES:
            raise ValueError(f"unknown journal sync policy {self.sync!r}; "
                             f"expected one of {SYNC_POLICIES}")
        self.snapshot_every = int(
            snapshot_every if snapshot_every is not None
            else flag("FLAGS_serving_snapshot_every", 64))
        self._lock = threading.RLock()
        self.records: Dict[int, JournalRecord] = {}
        self._terminal_order: List[int] = []
        self._next_jid = 0
        self._snap_seq = 0
        # recovery/observability counters (audit + tests read these)
        self.torn_tail_bytes = 0        # bytes truncated off the WAL tail
        self.snapshot_fallbacks = 0     # corrupt snapshots skipped at load
        self.recovered_records = 0      # records restored by _load()
        self.snapshots_written = 0
        self.flushes = 0
        self.appended_records = 0
        self._load()
        self._fh = open(self._wal_path, "ab")
        self._dirty = False

    # ------------------------------------------------------------------
    # paths
    @property
    def _wal_path(self) -> str:
        return os.path.join(self.dir, WAL_NAME)

    def _snapshot_paths(self) -> List[str]:
        """Snapshot files, newest first."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        snaps = sorted((n for n in names
                        if n.startswith(SNAPSHOT_PREFIX)
                        and n.endswith(SNAPSHOT_SUFFIX)), reverse=True)
        return [os.path.join(self.dir, n) for n in snaps]

    # ------------------------------------------------------------------
    # recovery (load at open)
    def _load(self) -> None:
        """Restore the mirror: newest GOOD snapshot (corrupt generations
        skipped), then replay the WAL suffix past its offset. Truncates a
        torn WAL tail in place so the next append starts clean."""
        wal_offset = self._load_snapshot()
        try:
            with open(self._wal_path, "rb") as fh:
                raw = fh.read()
        except FileNotFoundError:
            raw = b""
        if wal_offset > len(raw):
            # the WAL was truncated below the snapshot's fsynced offset
            # (torn_journal_tail chaos cutting deep): the snapshot IS the
            # last good state — nothing newer survives to replay.
            wal_offset = len(raw)
            events, good = [], len(raw)
        else:
            events, good = _parse_frames(raw, wal_offset)
        if good < len(raw):
            self.torn_tail_bytes += len(raw) - good
            with open(self._wal_path, "r+b") as fh:
                fh.truncate(good)
                fh.flush()
                os.fsync(fh.fileno())
        for ev in events:
            self._apply(ev)
        self.recovered_records = len(self.records)
        if self.records:
            self._next_jid = max(self._next_jid,
                                 max(self.records) + 1)

    def _load_snapshot(self) -> int:
        """Load the newest snapshot that verifies; returns the WAL offset
        it covers (0 when none loads — full replay)."""
        for path in self._snapshot_paths():
            try:
                with open(path, "rb") as fh:
                    raw = fh.read()
                events, _ = _parse_frames(raw)
                if len(events) != 1:
                    raise ValueError("bad snapshot frame")
                snap = events[0]
                records = {int(d["jid"]): JournalRecord.from_dict(d)
                           for d in snap["records"]}
            except (OSError, ValueError, KeyError, TypeError):
                self.snapshot_fallbacks += 1
                continue
            self.records = records
            self._terminal_order = [r.jid for r in records.values()
                                    if r.terminal]
            self._next_jid = int(snap.get("next_jid", 0))
            seq = os.path.basename(path)[len(SNAPSHOT_PREFIX):
                                         -len(SNAPSHOT_SUFFIX)]
            try:
                self._snap_seq = int(seq) + 1
            except ValueError:
                pass
            return int(snap.get("wal_offset", 0))
        return 0

    # ------------------------------------------------------------------
    # event application (the mirror's state machine)
    def _apply(self, ev: Dict) -> None:
        kind = ev.get("ev")
        jid = int(ev.get("jid", -1))
        if kind == "submit":
            self.records[jid] = JournalRecord.from_dict(ev)
        elif kind == "tok":
            rec = self.records.get(jid)
            if rec is not None and not rec.terminal:
                rec.tokens.extend(int(t) for t in ev.get("toks", ()))
        elif kind == "rebase":
            # ownership transfer (migration / handoff / hedge win): the
            # new owner's delivered cursor REPLACES the record's tokens
            rec = self.records.get(jid)
            if rec is not None and not rec.terminal:
                rec.tokens = [int(t) for t in ev.get("toks", ())]
        elif kind == "end":
            rec = self.records.get(jid)
            if rec is not None and not rec.terminal:
                rec.state = str(ev.get("state", "failed"))
                self._terminal_order.append(jid)
                while len(self._terminal_order) > KEEP_TERMINAL:
                    old = self._terminal_order.pop(0)
                    self.records.pop(old, None)

    def _append(self, ev: Dict) -> None:
        self._fh.write(_frame(json.dumps(ev).encode("utf-8")))
        self.appended_records += 1
        self._dirty = True
        if self.sync == "always":
            self._fh.flush()
            os.fsync(self._fh.fileno())
        self._apply(ev)

    # ------------------------------------------------------------------
    # logging API (called under the engine lock)
    def log_submit(self, *, prompt, max_new_tokens: int,
                   eos_token_id: Optional[int], temperature: float,
                   top_k: Optional[int], top_p: Optional[float],
                   seed: int, tenant: str, priority: int,
                   deadline: Optional[float],
                   adapter_id: Optional[str] = None,
                   tokens: Iterable[int] = ()) -> int:
        """Journal a newly admitted request's RESOLVED record; returns its
        journal-global jid. ``tokens`` seeds the delivered cursor for a
        resubmission whose original record is unknown to this journal."""
        with self._lock:
            jid = self._next_jid
            self._next_jid += 1
            self._append({
                "ev": "submit", "jid": jid,
                "prompt": [int(t) for t in np.asarray(prompt).ravel()],
                "max_new_tokens": int(max_new_tokens),
                "eos_token_id": (None if eos_token_id is None
                                 else int(eos_token_id)),
                "temperature": float(temperature),
                "top_k": None if top_k is None else int(top_k),
                "top_p": None if top_p is None else float(top_p),
                "seed": int(seed), "tenant": str(tenant),
                "priority": int(priority),
                "deadline": None if deadline is None else float(deadline),
                "adapter_id": (None if adapter_id is None
                               else str(adapter_id)),
                "tokens": [int(t) for t in tokens],
            })
            # admission is a durability point of its own: submit() acks
            # the request to the client, so the record must survive a
            # kill -9 landing BEFORE the step-batched flush — token
            # events stay batched, accepted requests are never lost
            self._fh.flush()
            if self.sync != "off":
                os.fsync(self._fh.fileno())
            self._dirty = False
            return jid

    def resume(self, jid: int, tokens: Iterable[int]) -> bool:
        """Re-attach a live record to a resubmitted/adopted/promoted copy.

        Returns False when the record is unknown or already terminal (the
        caller falls back to ``log_submit``). When the new owner's
        delivered cursor differs from the record's (a hedge copy whose
        emission ran ahead/behind delivery), a ``rebase`` event re-aligns
        the journal to what the client actually saw. Writes NOTHING when
        cursors already match — recovery's resubmits are idempotent, so a
        second crash during recovery replays to the same state."""
        with self._lock:
            rec = self.records.get(jid)
            if rec is None or rec.terminal:
                return False
            toks = [int(t) for t in tokens]
            if toks != rec.tokens:
                self._append({"ev": "rebase", "jid": jid, "toks": toks})
            return True

    def log_tokens(self, jid: int, toks: Iterable[int]) -> None:
        with self._lock:
            toks = [int(t) for t in toks]
            if toks:
                self._append({"ev": "tok", "jid": jid, "toks": toks})

    def log_terminal(self, jid: int, state: str) -> None:
        """Journal a terminal transition (idempotent: re-ending a record
        that is already terminal is a no-op, so recovery can re-run)."""
        with self._lock:
            rec = self.records.get(jid)
            if rec is None or rec.terminal:
                return
            self._append({"ev": "end", "jid": jid, "state": str(state)})

    # ------------------------------------------------------------------
    # durability points
    def flush(self, sync: Optional[bool] = None) -> None:
        """The once-per-engine-step durability point: flush buffered
        appends and (policy permitting) fsync. Auto-snapshots every
        ``snapshot_every`` flushes."""
        with self._lock:
            if self._dirty:
                self._fh.flush()
                do_sync = sync if sync is not None else self.sync != "off"
                if do_sync:
                    os.fsync(self._fh.fileno())
                self._dirty = False
            self.flushes += 1
            if self.snapshot_every > 0 \
                    and self.flushes % self.snapshot_every == 0:
                self.snapshot()

    def snapshot(self) -> str:
        """Write a snapshot of the mirror + the WAL offset it covers
        (tmp + fsync + ``os.replace`` — the PR 1 idiom; a crash mid-write
        leaves the previous generation intact). Keeps the newest
        ``KEEP_SNAPSHOTS`` generations."""
        with self._lock:
            # the snapshot may only cover DURABLE wal bytes: fsync first
            self._fh.flush()
            if self.sync != "off":
                os.fsync(self._fh.fileno())
            self._dirty = False
            offset = self._fh.tell()
            payload = json.dumps({
                "format": 1,
                "next_jid": self._next_jid,
                "wal_offset": offset,
                "records": [r.to_dict() for r in self.records.values()],
            }).encode("utf-8")
            name = f"{SNAPSHOT_PREFIX}{self._snap_seq:08d}{SNAPSHOT_SUFFIX}"
            self._snap_seq += 1
            path = os.path.join(self.dir, name)
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(_frame(payload))
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            self.snapshots_written += 1
            for old in self._snapshot_paths()[KEEP_SNAPSHOTS:]:
                try:
                    os.unlink(old)
                except OSError:
                    pass
            return path

    def close(self) -> None:
        with self._lock:
            if self._fh.closed:
                return
            self._fh.flush()
            if self.sync != "off":
                os.fsync(self._fh.fileno())
            self._fh.close()

    def abandon(self) -> int:
        """Simulate kill -9 (the ``process_kill`` chaos injector's
        in-process spelling): the userspace write buffer dies with the
        process — any append since the last :meth:`flush` never reaches
        the kernel — and the handle is dropped WITHOUT the graceful
        close's flush. On disk the WAL is exactly what the last flush
        made durable. Returns the surviving WAL size in bytes. The
        instance is unusable afterwards; recovery opens a NEW
        ``RequestJournal(journal_dir)``."""
        with self._lock:
            try:
                durable = os.path.getsize(self._wal_path)
            except OSError:
                durable = 0
            if self._fh.closed:
                return durable
            # closing a buffered writer flushes it — undo that below so
            # the un-flushed tail is lost, as it would be under SIGKILL
            try:
                self._fh.close()
            except OSError:
                pass
            try:
                with open(self._wal_path, "r+b") as fh:
                    fh.truncate(durable)
            except OSError:
                pass
            return durable

    # ------------------------------------------------------------------
    # recovery reads
    def live(self) -> Dict[int, JournalRecord]:
        """Non-terminal records, in jid (submission) order — exactly the
        set a cold restart must resubmit or close out."""
        with self._lock:
            return {j: self.records[j] for j in sorted(self.records)
                    if not self.records[j].terminal}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            live = sum(1 for r in self.records.values() if not r.terminal)
            return {"records": len(self.records), "live": live,
                    "appended": self.appended_records,
                    "flushes": self.flushes,
                    "snapshots_written": self.snapshots_written,
                    "snapshot_fallbacks": self.snapshot_fallbacks,
                    "torn_tail_bytes": self.torn_tail_bytes,
                    "recovered_records": self.recovered_records}
