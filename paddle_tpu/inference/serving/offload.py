"""Host-RAM KV offload tier (ISSUE 16 tentpole a): survivable cached
blocks.

The paged serving engine's prefix cache keeps refcount-0 blocks device-
resident until allocation pressure LRU-evicts them — and an evicted block
is recomputed from scratch on the next prefix hit. This module gives
evicted blocks a second life: :class:`HostOffloadTier` is a bounded
host-side pool that registered blocks swap into *instead of dying* when
the :class:`~paddle_tpu.inference.serving.paged_cache.BlockManager`
evicts them (the ``alloc()`` LRU branch and the tenant-quota recycle in
``register()`` — which also covers a preemption victim's registered
blocks, released to the evictable list and squeezed out later). A
subsequent prefix hit or victim readmission H2D-restores the chain
through ``PagedKVCache.admit()`` with zero recompute; if the bounded
tier itself evicted the entry, admission falls through to the existing
recompute path bit-exactly.

Design points:

* **Asynchronous swap-out.** ``put()`` captures per-leaf DEVICE slices
  of the dying block (``pool[leaf][:, b]`` — a copy is dispatched, the
  host does not block) into a small pending window, riding the same
  double-buffer idea as ``io.dataloader.prefetch_to_device``: the D2H
  materialization (``np.asarray``) of the oldest pending entry happens
  only when a newer eviction pushes it out of the window, on lookup, or
  at ``flush()`` — device work and the copy overlap instead of
  serializing the allocator on a transfer.
* **Write-time checksums.** Every leaf materializes with a CRC32 stamped
  at write time; ``take()`` re-verifies tokens AND checksums, so a
  corrupt host block (bit-rot, a chaos ``corrupt_offload_block``)
  degrades to a cache MISS — recompute, never wrong KV. This extends
  the PR 5 ``BlockManager.lookup()`` verification contract to the tier.
* **Move semantics.** A successful ``take()`` removes the entry: a block
  key is device-resident XOR host-resident (the auditor's
  ``tier_partition`` check), and ``BlockManager.register()`` discards
  any stale host copy when a key re-registers on device.
* **Bounded.** At ``capacity`` blocks the least-recently-written entry
  is dropped (``tier_evictions``); ``resize()`` shrinks the bound live
  (the ``host_pressure`` chaos injector). int8-quantized blocks are
  ~3.5x cheaper per block, so one bound holds ~3.5x the cached tokens.

No jax import here — like ``paged_cache`` this module only calls
methods on the array objects it is handed; device math stays in
``models/generation.py``.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["HostOffloadTier", "block_crc"]


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


# the one checksum the whole KV-movement surface shares: tier puts/takes,
# cross-replica chain pulls (engine.export_chain/graft_chain) and their
# chaos injectors all stamp and verify with this
block_crc = _crc


class HostOffloadTier:
    """Bounded host-RAM pool of swapped-out KV blocks, keyed by the same
    chained content hash the device prefix cache uses."""

    def __init__(self, capacity_blocks: int, block_size: int,
                 pending_depth: int = 2):
        self.capacity = max(0, int(capacity_blocks))
        self.block_size = int(block_size)
        self.pending_depth = max(0, int(pending_depth))
        # key -> {"tokens": tuple, "data": {leaf: np.ndarray}, "crc": {...}}
        self._entries: "OrderedDict[int, Dict]" = OrderedDict()
        # key -> (tokens, {leaf: device-array slice}) — swap-outs whose D2H
        # has been dispatched but not yet materialized (the double buffer)
        self._pending: "OrderedDict[int, Tuple[tuple, Dict]]" = OrderedDict()
        self.swap_outs = 0        # blocks accepted into the tier
        self.swap_ins = 0         # blocks restored to device by admit()
        self.tier_hits = 0        # verified take() hits
        self.tier_misses = 0      # take() for an absent key
        self.corrupt_drops = 0    # entries dropped on checksum/token mismatch
        self.tier_evictions = 0   # entries dropped by the capacity bound
        # fleet cache directory invalidation (ISSUE 17): called with the
        # key of EVERY entry that leaves the tier without re-registering
        # on device in the same operation (capacity eviction, discard,
        # verified take — the take's device re-registration re-adds the
        # key immediately after). None = no listener.
        self.on_drop = None

    def _dropped(self, key: int) -> None:
        if self.on_drop is not None:
            self.on_drop(key)

    # -- capacity -----------------------------------------------------------

    @property
    def blocks(self) -> int:
        """Blocks currently host-resident (materialized + pending)."""
        return len(self._entries) + len(self._pending)

    def keys(self):
        """Every key the tier currently holds (materialized + pending)."""
        yield from self._entries
        yield from self._pending

    def _evict_to(self, bound: int) -> None:
        while self.blocks > bound:
            if self._pending:   # oldest swap-out first (it is the LRU-est)
                k, _ = self._pending.popitem(last=False)
            else:
                k, _ = self._entries.popitem(last=False)
            self.tier_evictions += 1
            self._dropped(k)

    def resize(self, capacity_blocks: int) -> None:
        """Shrink/grow the bound live; excess entries fall back to the
        recompute path (the ``host_pressure`` chaos injector)."""
        self.capacity = max(0, int(capacity_blocks))
        self._evict_to(self.capacity)

    # -- swap-out -----------------------------------------------------------

    def put(self, key: int, tokens: tuple, slices: Dict) -> None:
        """Accept a dying block: ``slices`` maps pool leaf name to a
        device-array slice of the block (copy already dispatched). The
        host-side materialization is deferred (see module docstring)."""
        if self.capacity <= 0:
            return
        self._entries.pop(key, None)      # re-offload supersedes
        self._pending.pop(key, None)
        self._pending[key] = (tuple(tokens), dict(slices))
        self.swap_outs += 1
        while len(self._pending) > self.pending_depth:
            k, (toks, sl) = self._pending.popitem(last=False)
            self._materialize(k, toks, sl)
        self._evict_to(self.capacity)

    def _materialize(self, key: int, tokens: tuple, slices: Dict) -> None:
        data = {name: np.asarray(arr) for name, arr in slices.items()}
        self._entries[key] = {"tokens": tokens, "data": data,
                              "crc": {n: _crc(a) for n, a in data.items()}}

    def flush(self) -> None:
        """Materialize every pending swap-out (quiesce / audit barrier)."""
        while self._pending:
            k, (toks, sl) = self._pending.popitem(last=False)
            self._materialize(k, toks, sl)

    def holds(self, key: int) -> bool:
        """Whether the tier currently holds ``key`` (materialized or
        pending) — the residency test the BlockManager's directory
        invalidation consults when a device registration dies."""
        return key in self._entries or key in self._pending

    def discard(self, key: int) -> None:
        """Drop any host copy of ``key`` — called when the key registers
        on device again (device copy becomes the authoritative one)."""
        had = self._entries.pop(key, None) is not None
        had = self._pending.pop(key, None) is not None or had
        if had:
            self._dropped(key)

    # -- swap-in ------------------------------------------------------------

    def take(self, key: int, tokens) -> Optional[Dict]:
        """Verified move-out: return the block's host arrays iff the key
        is present, the stored token ids match ``tokens`` exactly, and
        every leaf's write-time checksum still verifies; the entry is
        removed on success (device becomes the resident tier). Any
        mismatch drops the entry and returns None — a MISS, so the
        caller recomputes; corruption is never attended."""
        if key in self._pending:
            toks, sl = self._pending.pop(key)
            self._materialize(key, toks, sl)
        e = self._entries.get(key)
        if e is None:
            self.tier_misses += 1
            return None
        if e["tokens"] != tuple(int(t) for t in tokens):
            del self._entries[key]
            self.corrupt_drops += 1
            self.tier_misses += 1
            self._dropped(key)
            return None
        for name, arr in e["data"].items():
            if _crc(arr) != e["crc"][name]:
                del self._entries[key]
                self.corrupt_drops += 1
                self.tier_misses += 1
                self._dropped(key)
                return None
        del self._entries[key]
        self.tier_hits += 1
        self._dropped(key)   # the caller registers it on device right away
        return e["data"]

    def peek(self, key: int, tokens) -> Optional[Dict]:
        """Verified NON-destructive read: the block's host arrays iff the
        key is present and tokens + every checksum verify, else None —
        the entry stays put either way (a cross-replica chain export
        COPIES the holder's cache, it must not steal it). Unlike
        :meth:`take`, a mismatch here does not drop the entry or charge
        ``corrupt_drops``: the holder's own next ``take`` will, through
        the accounting path its stats tests pin."""
        if key in self._pending:
            toks, sl = self._pending.pop(key)
            self._materialize(key, toks, sl)
        e = self._entries.get(key)
        if e is None or e["tokens"] != tuple(int(t) for t in tokens):
            return None
        for name, arr in e["data"].items():
            if _crc(arr) != e["crc"][name]:
                return None
        return e["data"]

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {"capacity": self.capacity, "blocks": self.blocks,
                "swap_outs": self.swap_outs, "swap_ins": self.swap_ins,
                "tier_hits": self.tier_hits, "tier_misses": self.tier_misses,
                "corrupt_drops": self.corrupt_drops,
                "tier_evictions": self.tier_evictions}

    def corrupt_one(self, seed: int = 0) -> Optional[int]:
        """Chaos hook (``corrupt_offload_block``): flip one byte in one
        stored leaf of a deterministic entry WITHOUT updating its
        checksum, so the next ``take()`` must detect it and degrade to a
        miss. Returns the corrupted key, or None when the tier is
        empty."""
        self.flush()
        if not self._entries:
            return None
        keys = list(self._entries)
        key = keys[seed % len(keys)]
        e = self._entries[key]
        name = sorted(e["data"])[seed % len(e["data"])]
        arr = np.array(e["data"][name], copy=True)
        flat = arr.reshape(-1).view(np.uint8)
        flat[seed % flat.size] ^= 0xFF
        e["data"][name] = arr
        return key
