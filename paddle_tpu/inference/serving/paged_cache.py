"""Paged KV cache — host-side block accounting over the device block pool.

The PagedAttention idea (vLLM) recast for the XLA serving stack: the device
holds ONE physical block pool ``{"k","v": [L, num_blocks, block_size, Hk,
D]}`` (:func:`paddle_tpu.models.generation.init_paged_pool`); a sequence
owns an ordered list of physical blocks recorded in its slot's row of the
block-table matrix, and the compiled decode step gathers exactly those
blocks. This module is the HOST half: a free-list block manager plus the
``[max_slots, W]`` block-table matrix the engine ships with every dispatch.
No jax import here — device math lives in ``models/generation.py``.

Allocation policy: blocks for a request's full worst-case KV footprint
(``prompt + max_new_tokens - 1`` entries) are reserved at admission, so a
running sequence can never hit a mid-flight out-of-blocks condition and the
engine needs no preemption/swap machinery (documented trade: admission is
conservative; docs/SERVING.md). Physical block 0 is the NULL block — the
masked-lane scatter target — and is never allocated.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

__all__ = ["BlockManager", "PagedKVCache"]


class BlockManager:
    """Free-list allocator over the physical block ids ``1..num_blocks-1``
    (block 0 = null). Double-free and foreign-id frees raise — a serving
    engine that corrupts its free list serves one sequence's KV to
    another, which must fail loudly."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 null + 1 usable), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: hot blocks are reused first (their pool pages are
        # the most likely still resident in any cache hierarchy)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set = set()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for(self, kv_tokens: int) -> int:
        """Physical blocks needed to hold ``kv_tokens`` KV entries."""
        return max(1, math.ceil(kv_tokens / self.block_size))

    def can_alloc(self, n: int) -> bool:
        return len(self._free) >= n

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"out of KV blocks: want {n}, "
                               f"free {len(self._free)}")
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        return blocks

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._allocated:
                raise RuntimeError(f"double/foreign free of block {b}")
            self._allocated.discard(b)
            self._free.append(b)


class PagedKVCache:
    """The device block pool + its host bookkeeping, per serving engine.

    ``tables`` is the ``[max_slots, W]`` int32 block-table matrix shipped
    with every decode dispatch (W = ceil(max_model_len / block_size));
    unassigned entries point at the null block 0 and are masked by the
    sequence-length mask on device.
    """

    def __init__(self, model_config, max_slots: int, max_model_len: int,
                 block_size: int, num_blocks: int = 0, dtype=None):
        from ...models.generation import init_paged_pool
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len)
        self.blocks_per_seq = max(1, math.ceil(max_model_len / block_size))
        if num_blocks <= 0:
            # auto-size: every slot can hold a full-length sequence, +1 null
            num_blocks = max_slots * self.blocks_per_seq + 1
        self.pool: Dict = init_paged_pool(model_config, num_blocks,
                                          block_size, dtype)
        self.manager = BlockManager(num_blocks, block_size)
        self.tables = np.zeros((max_slots, self.blocks_per_seq), np.int32)

    @property
    def free_blocks(self) -> int:
        return self.manager.free_blocks

    def reserve(self, kv_tokens: int) -> Optional[List[int]]:
        """Reserve blocks for a sequence's full KV footprint; None when the
        pool can't cover it right now (the request stays queued)."""
        n = self.manager.blocks_for(kv_tokens)
        if n > self.blocks_per_seq:
            raise ValueError(
                f"sequence needs {n} blocks ({kv_tokens} KV entries) but "
                f"max_model_len {self.max_model_len} caps block tables at "
                f"{self.blocks_per_seq}")
        if not self.manager.can_alloc(n):
            return None
        return self.manager.alloc(n)

    def assign(self, slot: int, blocks: List[int]) -> None:
        self.tables[slot] = 0
        self.tables[slot, :len(blocks)] = blocks

    def release(self, slot: int, blocks: List[int]) -> None:
        self.manager.free(blocks)
        self.tables[slot] = 0

    def kv_bytes(self) -> int:
        k = self.pool["k"]
        return 2 * k.size * k.dtype.itemsize
