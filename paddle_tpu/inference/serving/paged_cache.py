"""Paged KV cache — host-side block accounting over the device block pool.

The PagedAttention idea (vLLM) recast for the XLA serving stack: the device
holds ONE physical block pool ``{"k","v": [L, num_blocks, block_size, Hk,
D]}`` (:func:`paddle_tpu.models.generation.init_paged_pool`); a sequence
owns an ordered list of physical blocks recorded in its slot's row of the
block-table matrix, and the compiled decode step gathers exactly those
blocks. This module is the HOST half: a ref-counted block manager with a
content-hash prefix cache plus the ``[max_slots, W]`` block-table matrix
the engine ships with every dispatch. No jax import here — device math
lives in ``models/generation.py`` (the block copy helpers lazily import
jax only to pass block indices as DEVICE scalars, keeping one compiled
slice/update program across all block indices).

Allocation policy (ISSUE 5): **on-demand** — a sequence holds only the
blocks covering KV entries it has actually filled (admission maps/allocates
the prompt; decode extends block by block as ``seq_len`` grows). When the
pool runs dry mid-decode the ENGINE preempts the newest-admitted running
sequence (``scheduler.Scheduler.preempt``) instead of refusing progress.
The legacy reservation-at-admission policy (``prompt + max_new - 1``
entries reserved up front, no preemption needed) survives behind
``preempt=False`` / ``FLAGS_serving_preempt=0`` as a conservative
fallback, tested end-to-end. Physical block 0 is the NULL block — the
masked-lane scatter target — and is never allocated.

Prefix cache: every FULL block's token ids are content-hashed into a
CHAINED key (the key covers the whole block-aligned prefix, not just the
block — two different prefixes sharing one identical middle block must not
collide), so admissions sharing a system-prompt/few-shot prefix map the
cached blocks by refcount instead of re-running prefill over them. Blocks
whose refcount drops to 0 stay cached on an LRU list and are evicted only
when the free list runs dry.

Host offload tier (ISSUE 16): with a :class:`~paddle_tpu.inference.
serving.offload.HostOffloadTier` attached, an LRU-evicted registered
block swaps OUT to the bounded host pool instead of dying (both eviction
sites — ``alloc``'s LRU branch and ``register``'s tenant-quota recycle),
and ``admit``'s chain walk consults the tier on a device miss: a
verified host hit allocates a device block, H2D-restores the bytes, and
re-registers the key — zero recompute. A key is device-resident XOR
host-resident: registering a key on device discards any stale host copy,
and a successful host take moves the entry back to device.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BlockManager", "PagedKVCache", "prefix_block_chain"]


def prefix_block_chain(ids: Sequence[int], block_size: int, upto: int,
                       start: int = 0, prev_key: Optional[int] = None,
                       base: int = 0, namespace: Optional[str] = None):
    """Yield ``(key, tokens)`` for the FULL blocks ``start .. upto //
    block_size`` of a sequence — the ONE definition of the chained content
    key (lookup, registration and incremental resumption all walk this,
    so the formula cannot drift between them).

    Key ``i`` hashes (key ``i-1``, the ``block_size`` token ids of block
    ``i``), so equal keys imply equal whole block-aligned prefixes — a
    shared middle block under two different prefixes gets two different
    keys. Keys are still 64-bit hashes, so a hit is VERIFIED against the
    stored block tokens before mapping (:meth:`BlockManager.lookup`);
    ``tokens`` is yielded so registration can store them at zero extra
    cost. ``ids`` is indexed relative to ``base`` (``ids[i * block_size -
    base]`` is block ``i``'s first token), letting callers pass only the
    not-yet-registered tail instead of rebuilding the whole chain.

    ``namespace`` seeds the chain root (ISSUE 19): KV written under a
    LoRA adapter differs from base KV for the same tokens (the k/v
    projections carry the adapter delta), so each adapter hashes in its
    own disjoint key space — a base-cached block can never prefix-hit an
    adapter request or vice versa. ``None`` (base traffic) leaves the
    seed untouched, so every pre-LoRA key — including fleet directory
    entries and host-tier registrations — is bit-identical to before.
    """
    h = prev_key
    if h is None and namespace is not None:
        h = hash(("adapter-ns", namespace))
    for i in range(start, int(upto) // block_size):
        lo = i * block_size - base
        toks = tuple(int(t) for t in ids[lo:lo + block_size])
        h = hash((h, toks))
        yield h, toks


class BlockManager:
    """Ref-counted allocator over the physical block ids ``1..num_blocks-1``
    (block 0 = null) with a content-hash prefix cache.

    Lifecycle of a block: free list -> ``alloc`` (refcount 1) -> optionally
    ``register``\\ ed under its chained content key once its ``block_size``
    KV entries are written -> shared by later sequences via ``lookup`` +
    ``share`` (refcount++) -> ``free`` (refcount--) -> at refcount 0 a
    registered block parks on the EVICTABLE LRU list (still a cache hit!)
    while an unregistered one returns to the free list. ``alloc`` takes
    from the free list first and evicts LRU refcount-0 cached blocks only
    when that runs dry. Double-free and foreign-id frees raise — a serving
    engine that corrupts its accounting serves one sequence's KV to
    another, which must fail loudly.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 tenant_quota: Optional[int] = None):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 null + 1 usable), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # per-tenant prefix-cache quota (ISSUE 6): at most this many
        # blocks registered per tenant key — a tenant flooding unique
        # prompts churns its OWN cache entries instead of LRU-evicting
        # everyone else's system prompt. None = unlimited.
        self.tenant_quota = int(tenant_quota) if tenant_quota else None
        # LIFO free list: hot blocks are reused first (their pool pages are
        # the most likely still resident in any cache hierarchy)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}           # block -> live refcount
        self._hash2block: Dict[int, int] = {}    # chained key -> block
        self._block2hash: Dict[int, int] = {}
        # block -> its block_size token ids: lookup() verifies a hit
        # against these, so a 64-bit key collision degrades to a cache
        # MISS instead of silently mapping another sequence's KV
        self._block_tokens: Dict[int, Tuple[int, ...]] = {}
        # refcount-0 registered blocks, insertion order = LRU release order
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        # block -> registering tenant; tenant -> registered-block count
        self._block_tenant: Dict[int, str] = {}
        self._tenant_cached: Dict[str, int] = {}
        self.evictions = 0
        # host offload tier (ISSUE 16): installed by PagedKVCache when
        # FLAGS_serving_offload is on. `offload_capture(b)` returns the
        # per-leaf device slices of block b (the cache owns device I/O —
        # this module stays jax-free); `offload.put` accepts them.
        self.offload = None
        self.offload_capture = None
        # fleet cache directory (ISSUE 17): the router subscribes these
        # so its CacheDirectory learns which replica holds which chain
        # key. `notify_register(key)` fires when a key becomes device-
        # resident; `notify_unregister(key)` when it leaves the device
        # WITHOUT surviving in the host tier (the tier's own on_drop
        # covers the host side) — an entry can then never be
        # stale-authoritative, only stale-missing, which pulls degrade
        # from safely. None = no listener.
        self.notify_register = None
        self.notify_unregister = None

    @property
    def free_blocks(self) -> int:
        """Blocks allocatable RIGHT NOW: the free list plus the refcount-0
        cached blocks eviction can reclaim."""
        return len(self._free) + len(self._evictable)

    @property
    def cached_blocks(self) -> int:
        return len(self._hash2block)

    @property
    def blocks_in_use(self) -> int:
        return len(self._ref)

    def blocks_for(self, kv_tokens: int) -> int:
        """Physical blocks needed to hold ``kv_tokens`` KV entries."""
        return max(1, math.ceil(kv_tokens / self.block_size))

    def can_alloc(self, n: int) -> bool:
        return n <= self.free_blocks

    def alloc(self, n: int) -> List[int]:
        if n > self.free_blocks:
            raise RuntimeError(f"out of KV blocks: want {n}, "
                               f"free {self.free_blocks}")
        blocks = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:                                # LRU-evict a cached block
                b, _ = self._evictable.popitem(last=False)
                self._offload(b)
                self._unregister(b)
                self.evictions += 1
            self._ref[b] = 1
            blocks.append(b)
        return blocks

    def _offload(self, b: int) -> None:
        """Swap a dying registered block into the host tier (when one is
        attached) — called at both eviction sites, BEFORE the block's
        registration (key + verified tokens) is dropped. Blocks without
        stored tokens are skipped: the tier's verified-hit contract needs
        them."""
        if self.offload is None or self.offload_capture is None:
            return
        key = self._block2hash.get(b)
        toks = self._block_tokens.get(b)
        if key is not None and toks is not None:
            self.offload.put(key, toks, self.offload_capture(b))

    def _unregister(self, b: int) -> None:
        """Drop block ``b``'s prefix-cache registration (hash maps, stored
        tokens, tenant accounting). The caller owns what happens to the
        block itself."""
        key = self._block2hash.pop(b)
        del self._hash2block[key]
        if self.notify_unregister is not None and \
                not (self.offload is not None and self.offload.holds(key)):
            # both eviction sites _offload() BEFORE _unregister(), so a
            # key the tier accepted is still replica-resident — the
            # directory entry survives the swap-out
            self.notify_unregister(key)
        self._block_tokens.pop(b, None)
        t = self._block_tenant.pop(b, None)
        if t is not None:
            self._tenant_cached[t] -= 1
            if not self._tenant_cached[t]:
                del self._tenant_cached[t]

    def tenant_cached(self, tenant: str) -> int:
        """Registered prefix-cache blocks currently charged to a tenant."""
        return self._tenant_cached.get(tenant, 0)

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if self._ref.get(b, 0) <= 0:
                raise RuntimeError(f"double/foreign free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if b in self._block2hash:        # stays cached, evictable
                    self._evictable[b] = None
                else:
                    self._free.append(b)

    # ---- prefix cache ------------------------------------------------------

    def lookup(self, key: int,
               tokens: Optional[Tuple[int, ...]] = None) -> Optional[int]:
        """The cached block for a chained content key, or None. With
        ``tokens`` (the candidate block's ids) the hit is VERIFIED — an
        O(block_size) compare per block, so a hash collision can only
        cost a miss, never map another sequence's KV."""
        b = self._hash2block.get(key)
        if b is not None and tokens is not None \
                and self._block_tokens.get(b) != tokens:
            return None                          # unverifiable == miss
        return b

    def share(self, block: int) -> int:
        """Take a reference on a cached block (a prefix-cache hit mapping
        it into another sequence's table)."""
        if block in self._evictable:             # revive from the LRU list
            del self._evictable[block]
            self._ref[block] = 1
        elif self._ref.get(block, 0) > 0:
            self._ref[block] += 1
        else:
            raise RuntimeError(f"share of unknown block {block}")
        return block

    def register(self, key: int, block: int,
                 tokens: Optional[Tuple[int, ...]] = None,
                 tenant: Optional[str] = None) -> None:
        """Content-hash a LIVE full block for prefix sharing. First writer
        wins: an already-registered key (another sequence beat us to the
        same prefix) or block is left alone. ``tokens`` (the block's ids)
        back :meth:`lookup`'s hit verification; without them a verified
        lookup of this key reports a miss.

        With a ``tenant_quota`` set and a ``tenant`` given, a tenant at
        its quota recycles its OWN least-recently-released refcount-0
        entry to make room — and when every one of its entries is still
        referenced, the registration is simply skipped (the block stays
        usable, just unshared). Either way the tenant cannot push another
        tenant's entries off the LRU list by flooding unique prompts."""
        if key in self._hash2block or block in self._block2hash:
            return
        if self._ref.get(block, 0) <= 0:
            raise RuntimeError(f"register of non-live block {block}")
        if self.tenant_quota is not None and tenant is not None and \
                self._tenant_cached.get(tenant, 0) >= self.tenant_quota:
            mine = next((b for b in self._evictable
                         if self._block_tenant.get(b) == tenant), None)
            if mine is None:
                return                   # quota full of pinned entries
            del self._evictable[mine]
            self._offload(mine)
            self._unregister(mine)
            self._free.append(mine)
            self.evictions += 1
        if self.offload is not None:
            # the device copy becomes the resident tier for this key — a
            # stale host copy must not survive (device XOR host residency)
            self.offload.discard(key)
        self._hash2block[key] = block
        self._block2hash[block] = key
        if tokens is not None:
            self._block_tokens[block] = tokens
        if self.notify_register is not None:
            self.notify_register(key)
        if tenant is not None:
            self._block_tenant[block] = tenant
            self._tenant_cached[tenant] = \
                self._tenant_cached.get(tenant, 0) + 1


class PagedKVCache:
    """The device block pool + its host bookkeeping, per serving engine.

    ``tables`` is the ``[max_slots, W]`` int32 block-table matrix shipped
    with every decode dispatch (W = ceil(max_model_len / block_size));
    unassigned entries point at the null block 0 and are masked by the
    sequence-length mask on device.
    """

    def __init__(self, model_config, max_slots: int, max_model_len: int,
                 block_size: int, num_blocks: int = 0, dtype=None,
                 prefix_cache: bool = True,
                 tenant_quota: Optional[int] = None, kv_quant=None,
                 mesh=None, offload: bool = False,
                 offload_blocks: int = 0):
        from ...models.generation import init_paged_pool
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len)
        self.prefix_cache = bool(prefix_cache)
        self.kv_quant = kv_quant
        # serving tensor parallelism (ISSUE 12): with a mesh, the pool
        # leaves are emitted sharded on their kv-heads axis over the "tp"
        # axis — every HOST structure here (block manager, tables, prefix
        # keys over token ids) is device-count-AGNOSTIC: block ids are
        # global, tables replicate, only pool bytes split across devices
        self.mesh = mesh
        self.tp = int(mesh.shape["tp"]) if mesh is not None else 1
        self.blocks_per_seq = max(1, math.ceil(max_model_len / block_size))
        if num_blocks <= 0:
            # auto-size: every slot can hold a full-length sequence, +1 null
            num_blocks = max_slots * self.blocks_per_seq + 1
        # kv_quant="int8": int8 K/V blocks + per-token-per-head fp32 scale
        # planes ride in the same pool pytree — every host-side structure
        # here (block manager, tables, prefix-cache keys over TOKEN IDS)
        # is layout-agnostic, so int8 blocks hash/hit/evict exactly like
        # fp blocks; only the device pool layout changes
        self.pool: Dict = init_paged_pool(model_config, num_blocks,
                                          block_size, dtype,
                                          kv_quant=kv_quant, mesh=mesh)
        self.manager = BlockManager(num_blocks, block_size,
                                    tenant_quota=tenant_quota)
        self.tables = np.zeros((max_slots, self.blocks_per_seq), np.int32)
        # host offload tier (ISSUE 16): evicted registered blocks swap to
        # a bounded host pool instead of dying; admit() restores them
        self.offload = None
        if offload and prefix_cache and offload_blocks > 0:
            from .offload import HostOffloadTier
            self.offload = HostOffloadTier(offload_blocks, block_size)
            self.manager.offload = self.offload
            self.manager.offload_capture = self.read_block

    @property
    def free_blocks(self) -> int:
        return self.manager.free_blocks

    # ---- device block I/O --------------------------------------------------

    def read_block(self, block: int) -> Dict:
        """Per-leaf device slices of one physical block (``pool[leaf][:,
        b]`` — the copy is DISPATCHED here, not materialized: np.asarray
        on a returned slice blocks for the D2H). Shared by the offload
        tier's swap-out capture and migration's chain serialization.

        The block index crosses as a DEVICE scalar: a python int bakes
        into the sliced executable as a constant, so a churning tier
        would compile one slice program per distinct block index
        (measured ~50ms each on XLA:CPU — dwarfing the copy itself)."""
        import jax
        import jax.numpy as jnp  # local: module stays jax-free at import

        b = jnp.asarray(block, jnp.int32)
        return {name: jax.lax.dynamic_index_in_dim(arr, b, axis=1,
                                                   keepdims=False)
                for name, arr in self.pool.items()}

    def write_block(self, block: int, data: Dict) -> None:
        """H2D-write one physical block's per-leaf host arrays back into
        the pool — the offload tier's swap-in restore. Same device-scalar
        index discipline as ``read_block`` (one compiled update program
        for every block index, not one per index)."""
        import jax
        import jax.numpy as jnp  # local: module stays jax-free at import

        b = jnp.asarray(block, jnp.int32)
        for name, arr in self.pool.items():
            self.pool[name] = jax.lax.dynamic_update_index_in_dim(
                arr, jnp.asarray(data[name], arr.dtype), b, axis=1)

    def write_blocks(self, blocks: List[int], data: Dict) -> None:
        """H2D-write a gathered run of blocks (``data[leaf]`` carries the
        block axis at position 1: ``[L, len(blocks), ...]``) — the
        migration adopt path's bulk restore."""
        idx = np.asarray(blocks, np.int32)
        for name, arr in self.pool.items():
            self.pool[name] = arr.at[:, idx].set(data[name])

    # ---- admission ---------------------------------------------------------

    def admit(self, ids: np.ndarray,
              reserve_kv: Optional[int] = None,
              namespace: Optional[str] = None
              ) -> Optional[Tuple[List[int], int, Tuple[int, Optional[int]]]]:
        """Map + allocate blocks for a sequence entering prefill.

        ``ids`` are the tokens prefill will compute (the prompt, or prompt
        + already-generated tokens on post-preemption readmission). With
        the prefix cache on, the longest chain of cached full blocks over
        ``ids[:-1]`` is SHARED into the sequence (capped one token short of
        the whole sequence so at least one token always runs through
        prefill — the next-token logits have to come from somewhere); only
        the remainder is allocated. ``reserve_kv`` switches to the legacy
        worst-case reservation (allocate the full ``prompt + max_new - 1``
        footprint now — the ``preempt=False`` mode). ``namespace``
        (ISSUE 19) is the request's adapter id — it seeds the content
        chain so adapter KV and base KV never cross-hit (see
        :func:`prefix_block_chain`). Returns ``(blocks,
        hit_tokens, reg_state)`` — ``reg_state`` seeds
        :meth:`register_prefix` at the hit boundary so later registration
        never re-hashes the hit chain — or None when the pool can't cover
        it right now (the request stays queued; admission never preempts
        running work).
        """
        n_tokens = int(reserve_kv) if reserve_kv is not None else len(ids)
        n_total = self.manager.blocks_for(n_tokens)
        if n_total > self.blocks_per_seq:
            raise ValueError(
                f"sequence needs {n_total} blocks ({n_tokens} KV entries) "
                f"but max_model_len {self.max_model_len} caps block tables "
                f"at {self.blocks_per_seq}")
        hits: List[int] = []
        last_key: Optional[int] = None
        if self.prefix_cache:
            # pin-as-we-go: each hit is share()d the moment it verifies, so
            # a host-tier restore's alloc (which may itself LRU-evict) can
            # never evict a block we are about to map
            for key, toks in prefix_block_chain(ids, self.block_size,
                                                len(ids) - 1,
                                                namespace=namespace):
                b = self.manager.lookup(key, toks)
                if b is not None:
                    self.manager.share(b)
                    hits.append(b)
                    last_key = key
                    continue
                if self.offload is not None and self.manager.can_alloc(1):
                    # device miss — consult the host tier. A verified take
                    # H2D-restores the block and re-registers the key: the
                    # chain continues with zero recompute. A miss (absent,
                    # evicted, or checksum-failed) breaks to the recompute
                    # path exactly as before the tier existed.
                    data = self.offload.take(key, toks)
                    if data is not None:
                        [b] = self.manager.alloc(1)
                        self.write_block(b, data)
                        self.manager.register(key, b, toks)
                        self.offload.swap_ins += 1
                        hits.append(b)
                        last_key = key
                        continue
                break
        n_new = n_total - len(hits)
        if not self.manager.can_alloc(n_new):
            if hits:
                self.manager.free(hits)
            return None
        return (hits + self.manager.alloc(n_new),
                len(hits) * self.block_size, (len(hits), last_key))

    def extend(self, slot: int, blocks: List[int],
               kv_tokens: int) -> Optional[List[int]]:
        """Grow a slot's block list (in place) to cover ``kv_tokens`` KV
        entries — the on-demand decode path. Returns the newly allocated
        blocks ([] when already covered), or None when the pool is dry
        (the engine then preempts)."""
        n = self.manager.blocks_for(kv_tokens) - len(blocks)
        if n <= 0:
            return []
        if not self.manager.can_alloc(n):
            return None
        new = self.manager.alloc(n)
        self.tables[slot, len(blocks):len(blocks) + n] = new
        blocks.extend(new)
        return new

    def register_prefix(self, ids, blocks: List[int], upto: int,
                        state: Tuple[int, Optional[int]] = (0, None),
                        base: int = 0, tenant: Optional[str] = None,
                        namespace: Optional[str] = None
                        ) -> Tuple[int, Optional[int]]:
        """Register the full blocks covering KV entries ``[..upto)`` (those
        the device has finished writing) in the prefix cache,
        INCREMENTALLY: ``state`` is ``(blocks already registered, chained
        key of the last one)`` from the previous call (or ``admit``'s hit
        boundary), so each block's tokens are hashed exactly once over a
        sequence's lifetime — a per-dispatch full-chain re-hash would make
        the continuous-batching host loop O(seq_len^2) per request. For
        the same reason ``ids`` may be just the not-yet-registered TAIL
        with ``base`` naming its first KV position (``ids[p - base]``
        backs entry ``p``). Returns the advanced state; the caller keeps
        it on the request."""
        if not self.prefix_cache:
            return state
        n, h = state
        for key, toks in prefix_block_chain(ids, self.block_size, upto,
                                            start=n, prev_key=h, base=base,
                                            namespace=namespace):
            self.manager.register(key, blocks[n], toks, tenant=tenant)
            n, h = n + 1, key
        return (n, h)

    def assign(self, slot: int, blocks: List[int]) -> None:
        self.tables[slot] = 0
        self.tables[slot, :len(blocks)] = blocks

    def release(self, slot: int, blocks: List[int]) -> None:
        self.manager.free(blocks)
        self.tables[slot] = 0

    def kv_bytes(self, per_shard: bool = False) -> int:
        """Device bytes the pool holds — every leaf (K + V, plus the scale
        planes on quantized layouts), the number capacity planning and the
        ``kv_pool_bytes`` ops field report. ``per_shard=True`` returns the
        bytes ONE device holds under tensor parallelism (the global total
        divided by the TP degree — the kv-heads split is exact): the
        number a per-chip HBM budget must cover, and the
        ``kv_pool_shard_bytes`` ops field."""
        total = sum(a.size * a.dtype.itemsize for a in self.pool.values())
        return total // self.tp if per_shard else total
