"""Pluggable admission policies — WHO gets the next free slot.

The FIFO queue PR 4 shipped is the right default for a batch replayer and
the wrong one for millions of users: one tenant's burst starves everyone
else, a latency-insensitive bulk job admits ahead of an interactive
request ten times over its TTFT budget, and "first come" is the only
lever an operator has. This module turns the admission decision into a
strategy object the :class:`~.scheduler.Scheduler` consults each
iteration, with four shipped policies:

=============  =============================================================
policy         admission order
=============  =============================================================
``fifo``       submission order (the default — and the parity oracle the
               policy tests pin every other policy's OUTPUTS against:
               admission order must never change a request's tokens)
``priority``   higher ``Request.priority`` first; FIFO within a class
``fair``       weighted fair share across ``Request.tenant``: the queued
               tenant with the least weighted service (prefill + decode
               tokens, divided by its weight) admits next; FIFO within a
               tenant
``edf``        earliest deadline first: the queued request whose
               ``deadline`` (from ``submit(timeout_s=/deadline_s=)``, or
               ``submit_t + default_ttft_slo_s`` when none) expires
               soonest admits next — the TTFT-SLO scheduler the overload
               bench row measures against FIFO
=============  =============================================================

Two properties every policy inherits from the scheduler, not from this
module: a PREEMPTED request re-queued at the front always readmits ahead
of the policy's pick (its tokens are already paid for, and the
no-livelock argument needs it back in a slot at the next retirement), and
admission is still head-of-line per the policy's order — if the pick's
blocks don't fit, admission waits for a retirement rather than skipping
to a smaller request (skipping would starve large requests forever).

Policies only reorder ADMISSION. Greedy decode is deterministic per
request, so any admission order yields bit-identical per-request outputs
— ``tests/test_serving.py`` pins every shipped policy against the FIFO
oracle.

Across an :class:`~.supervisor.EngineSupervisor` restart (ISSUE 7) the
same holds: resubmission re-queues survivors in original submission
order, and each policy re-derives its order from request attributes
(``priority`` / ``deadline`` / ``tenant``) that survive the rebuild.
The one lossy input is fair share's ``service_tokens`` accounting, which
restarts from zero with the fresh scheduler — a restart briefly levels
the playing field rather than starving anyone, which is the safe
direction to err.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

__all__ = ["AdmissionPolicy", "FIFOPolicy", "PriorityPolicy",
           "FairSharePolicy", "EDFPolicy", "POLICIES", "resolve_policy"]


class AdmissionPolicy:
    """Strategy interface: pick which queued request admits next.

    ``select`` sees the live queue (never empty), the scheduler (for
    tenant service accounting), and the current time; it must return one
    of the queued requests and must not mutate the queue.
    """

    name = "fifo"

    def select(self, queue: Sequence, sched, now: float):
        return queue[0]


class FIFOPolicy(AdmissionPolicy):
    """Submission order — the default and the behavioral baseline."""


class PriorityPolicy(AdmissionPolicy):
    """Strict priority classes: highest ``Request.priority`` first, FIFO
    within a class. No aging — a saturated high class starves lower ones
    by design (pair with deadlines/timeouts if that is not acceptable)."""

    name = "priority"

    def select(self, queue, sched, now):
        return max(queue, key=lambda r: (r.priority, -r.rid))


class FairSharePolicy(AdmissionPolicy):
    """Weighted fair share across tenants: admit the queued tenant with
    the least weighted service so far. Service is the tokens the engine
    has actually spent on the tenant (prompt tokens at admission + decode
    tokens at retirement, ``Scheduler.tenant()['service_tokens']``);
    weights default to 1.0 per tenant, so a tenant flooding the queue
    gets the same share as everyone else instead of the whole engine —
    the ``flood_tenant`` chaos injector's recovery proof."""

    name = "fair"

    def __init__(self, weights: Optional[Dict[str, float]] = None):
        self.weights = dict(weights or {})

    def select(self, queue, sched, now):
        def share(t: str) -> float:
            w = max(self.weights.get(t, 1.0), 1e-9)
            return sched.tenant(t)["service_tokens"] / w

        best = min({r.tenant for r in queue}, key=lambda t: (share(t), t))
        return next(r for r in queue if r.tenant == best)


class EDFPolicy(AdmissionPolicy):
    """Earliest deadline first. A request's effective deadline is its
    explicit one (``submit(timeout_s=/deadline_s=)``) or ``submit_t +
    default_ttft_slo_s`` when the policy carries a default SLO; requests
    with neither sort last (FIFO among themselves). The engine sheds
    queued requests whose explicit deadline already passed before they
    waste prefill — EDF orders the rest so the tightest feasible SLOs are
    met first (the overload bench row's p99-TTFT win over FIFO)."""

    name = "edf"

    def __init__(self, default_ttft_slo_s: Optional[float] = None):
        self.default_ttft_slo_s = (float(default_ttft_slo_s)
                                   if default_ttft_slo_s else None)

    def _deadline(self, req) -> float:
        if req.deadline is not None:
            return req.deadline
        if self.default_ttft_slo_s is not None:
            return req.submit_t + self.default_ttft_slo_s
        return float("inf")

    def select(self, queue, sched, now):
        return min(queue, key=lambda r: (self._deadline(r), r.rid))


POLICIES = {
    "fifo": FIFOPolicy,
    "priority": PriorityPolicy,
    "fair": FairSharePolicy,
    "edf": EDFPolicy,
}


def resolve_policy(spec, ttft_slo_s: Optional[float] = None
                   ) -> AdmissionPolicy:
    """An :class:`AdmissionPolicy` from a config value: an instance
    passes through (programmatic weights/SLOs), a name constructs the
    registered class (``edf`` picks up ``ttft_slo_s`` — the
    ``FLAGS_serving_ttft_slo_s`` default), None means FIFO."""
    if isinstance(spec, AdmissionPolicy):
        return spec
    if spec is None:
        return FIFOPolicy()
    name = str(spec).lower().replace("-", "_").replace("fair_share", "fair")
    if name not in POLICIES:
        raise ValueError(f"unknown admission policy {spec!r}; "
                         f"options: {sorted(POLICIES)}")
    if name == "edf":
        return EDFPolicy(default_ttft_slo_s=ttft_slo_s)
    return POLICIES[name]()
