"""One serving replica as the router sees it: a supervised engine stack
plus the health machinery that decides whether traffic may land on it
(docs/OPS.md "Serving fleet").

A :class:`Replica` wraps one :class:`~.supervisor.EngineSupervisor` (the
full PR-7 stack: crash barrier, restart budget, graceful drain) behind the
two things a router needs:

* **A probe surface.** :meth:`Replica.probe` is the in-process spelling of
  ``GET /readyz`` + ``health_snapshot()``: it returns the supervisor's
  snapshot, or raises — and a raising probe is ITSELF a health signal the
  circuit breaker consumes (the ``flaky_probe`` chaos injector models a
  replica whose ops surface is wedged even though the engine might not
  be).

* **A circuit breaker.** :class:`CircuitBreaker` is the classic three
  states: CLOSED passes traffic and counts consecutive failures; at the
  threshold it OPENS and the router routes around the replica entirely; a
  cooldown later the router re-probes HALF-OPEN — one probe, no user
  traffic at risk — and the breaker either closes (the replica rejoins
  the candidate set) or re-opens with a fresh cooldown. Every transition
  is counted (``opens`` / ``half_open_probes`` / ``reclosures``) and
  surfaced in the router's ``health_snapshot()`` so ops can see a flapping
  replica from ``/metrics``.

The replica also carries the rolling-restart bookkeeping (``generation``
bumps every rebuild, ``draining``/``retiring`` gate routing) — the router
owns the policy, the replica owns the state.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ...flags import flag
from .supervisor import EngineSupervisor

__all__ = ["CircuitBreaker", "Replica",
           "BREAKER_CLOSED", "BREAKER_OPEN", "BREAKER_HALF_OPEN"]

BREAKER_CLOSED = "closed"          # traffic flows; failures counted
BREAKER_OPEN = "open"              # no traffic until the cooldown elapses
BREAKER_HALF_OPEN = "half_open"    # one probe in flight decides the rest


class CircuitBreaker:
    """Consecutive-failure breaker: ``threshold`` failures in a row OPEN
    it, ``cooldown_s`` later one HALF-OPEN probe decides between closing
    (success) and re-opening (failure). A failure while HALF-OPEN always
    re-opens — a single bad probe must not let a sick replica flap back
    into rotation."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None):
        self.threshold = int(
            threshold if threshold is not None
            else flag("FLAGS_serving_router_breaker_threshold"))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else flag("FLAGS_serving_router_breaker_cooldown_s"))
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opened_t: Optional[float] = None
        self.opens = 0
        self.half_open_probes = 0
        self.reclosures = 0            # closed again from half-open

    def allow(self) -> bool:
        """Whether the router may route traffic here right now. Only a
        CLOSED breaker passes traffic; HALF_OPEN passes only the health
        probe (which goes through :meth:`probe_started`, not here)."""
        return self.state == BREAKER_CLOSED

    def ready_to_probe(self, now: Optional[float] = None) -> bool:
        """An OPEN breaker whose cooldown has elapsed wants its half-open
        probe."""
        if self.state != BREAKER_OPEN:
            return False
        now = time.time() if now is None else now
        return self.opened_t is None or now - self.opened_t >= self.cooldown_s

    def probe_started(self) -> None:
        self.state = BREAKER_HALF_OPEN
        self.half_open_probes += 1

    def record_success(self) -> None:
        if self.state == BREAKER_HALF_OPEN:
            self.reclosures += 1
        self.consecutive_failures = 0
        self.state = BREAKER_CLOSED

    def record_failure(self, now: Optional[float] = None) -> None:
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN or \
                self.consecutive_failures >= self.threshold:
            self.trip(now)

    def trip(self, now: Optional[float] = None) -> None:
        """Force OPEN immediately (a broken replica does not get to count
        down the threshold)."""
        if self.state != BREAKER_OPEN:
            self.opens += 1
        self.state = BREAKER_OPEN
        self.opened_t = time.time() if now is None else now
        self.consecutive_failures = max(self.consecutive_failures,
                                        self.threshold)

    def reset(self) -> None:
        """A rebuilt replica starts with a clean breaker (the counters
        survive — flapping history is an ops signal)."""
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.opened_t = None

    def snapshot(self) -> Dict[str, Any]:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "opens": self.opens,
                "half_open_probes": self.half_open_probes,
                "reclosures": self.reclosures}


class Replica:
    """One supervised engine stack plus its router-side state. The
    supervisor object is REPLACEABLE (rolling restarts swap in a fresh
    one, bumping ``generation``); the replica identity — rid, breaker
    history, restart counters — survives the swap."""

    def __init__(self, rid: int, supervisor: EngineSupervisor,
                 breaker: Optional[CircuitBreaker] = None,
                 role: str = "decode"):
        self.rid = rid
        self.sup = supervisor
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # "decode" serves the full lifecycle; "prefill" (disaggregated
        # prefill, ISSUE 17) only runs prompts to their first token and
        # hands the chain to a decode replica — the router's candidate
        # sets filter on this, the role never changes after spawn
        self.role = role
        self.generation = 0            # bumps per rolling-restart rebuild
        self.retiring = False          # scale-in: remove once drained
        self.restarts_seen = 0         # supervisor restarts already counted
        self.broken_seen = False       # broken already failed over
        self.shed_seen = 0             # cumulative shed already folded into
        #                                the router's monotonic fleet total
        self.probe_cache: Optional[Dict[str, Any]] = None
        self.probe_t = 0.0             # router's probe TTL cache
        self.probe_depth = 0           # queued+live from the last probe
        #                                (the P2C comparison key)

    # ---- health ------------------------------------------------------------

    @property
    def draining(self) -> bool:
        return bool(self.sup.drain_requested or self.sup.draining)

    def probe(self) -> Dict[str, Any]:
        """The router's health probe: ``health_snapshot()`` (which folds
        in the ``/readyz`` predicate as ``accepting``). Raises when the
        replica's ops surface is wedged — the caller records that on the
        breaker."""
        return self.sup.health_snapshot()

    def routable(self) -> bool:
        """Whether NEW traffic may land here: breaker closed, not
        draining/retiring, restart budget intact, admission queue open.
        Never raises — a raising accepting-check counts as not routable
        (the probe path is where failures are charged)."""
        if not self.breaker.allow() or self.retiring or self.draining:
            return False
        try:
            return bool(self.sup.accepting)
        except Exception:              # noqa: BLE001 — wedged ops surface
            return False

    def adoptable(self) -> bool:
        """Whether work may still LAND here when the admission queue is
        full: breaker closed, not retiring/draining, restart budget
        intact. Weaker than :meth:`routable` (which also needs an open
        queue) — failover resubmit bypasses the queue bound (the work
        was accepted once, somewhere), and the submit path falls back to
        this set so plain overload sheds with the engine's structured
        429, not a misleading \"broken/circuit-broken\" 503."""
        return (self.breaker.allow() and not self.retiring
                and not self.draining and not self.sup.broken)

    def depth(self) -> int:
        """Queued + live work (the power-of-two-choices comparison key)."""
        return self.sup.depth()

    # ---- lifecycle ---------------------------------------------------------

    def replace(self, supervisor: EngineSupervisor) -> EngineSupervisor:
        """Swap in a freshly built supervisor (rolling restart): the old
        one is returned for inspection, the breaker resets to CLOSED and
        the crash bookkeeping re-bases on the new stack."""
        old, self.sup = self.sup, supervisor
        self.generation += 1
        self.restarts_seen = 0
        self.broken_seen = False
        self.shed_seen = 0             # the fresh supervisor counts from 0
        self.probe_cache = None        # never serve the dead stack's probe
        self.breaker.reset()
        return old

    def snapshot(self) -> Dict[str, Any]:
        """The per-replica row in the router's ``health_snapshot()``."""
        try:
            depth = self.depth()
        except Exception:              # noqa: BLE001
            depth = None
        return {"accepting": self.routable(),
                "role": self.role,
                "broken": bool(self.sup.broken),
                "draining": self.draining,
                "retiring": self.retiring,
                "generation": self.generation,
                "restarts": self.sup.restarts,
                "depth": depth,
                "breaker": self.breaker.snapshot()}
