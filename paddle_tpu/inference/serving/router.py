"""Multi-replica serving fleet: a health-aware router over N supervised
replicas (docs/OPS.md "Serving fleet", docs/SERVING.md "Serving fleet
router").

Everything PRs 4-7 built — the overload-safe engine, crash supervision,
graceful drain, autoscale telemetry — lives inside a SINGLE replica: one
replica exhausting its restart budget takes the whole service down.
:class:`ServingRouter` fronts N in-process replicas (each a full
:class:`~.supervisor.EngineSupervisor`/:class:`~.server.ServingServer`
stack) sharing ONE set of params and ONE compiled
:class:`~.engine.EnginePrograms` (an extra replica costs KV-pool memory,
never a recompile), behind the same ``submit()/step()/run()`` —
and, through :class:`ServingServer`, ``handle()/agenerate()`` — client
surface a single supervisor exposes:

* **Health-aware routing.** Each submit probes the candidate replicas
  (``/readyz`` predicate + ``health_snapshot()``; a RAISING probe is a
  breaker failure) and picks by POWER-OF-TWO-CHOICES on queue depth —
  sample two, take the shallower — with tenant/prefix-affinity
  stickiness: requests sharing a block-aligned prompt prefix keep landing
  on the replica already holding those KV blocks in its prefix cache.

* **Failover.** When a replica dies mid-stream — restart budget
  exhausted (``broken``), or its circuit breaker opens on a crash loop —
  every non-terminal request it held is resubmitted to a healthy replica
  from ``prompt + tokens delivered so far``
  (:meth:`~.supervisor.EngineSupervisor.resubmit` riding the
  preemption-recompute path): greedy outputs stay bit-identical to an
  uninterrupted run and no delivered token is ever repeated.

* **Self-protection.** A per-replica :class:`~.replica.CircuitBreaker`
  (consecutive-failure open -> cooldown -> half-open probe -> close on
  success) keeps traffic off a sick replica without giving up on it; an
  optional HEDGED RETRY duplicates a request still waiting for its first
  token past a TTFT-SLO multiple onto a second replica, first token wins,
  and the loser is cancelled through the lifecycle path so no KV blocks
  leak (greedy determinism makes the copies interchangeable).

* **One cache, split compute (ISSUE 17).** A fleet-wide
  :class:`~.directory.CacheDirectory` tracks which replica holds every
  chained prefix key (fed by BlockManager/offload-tier callbacks the
  router wires into each replica): a submit finds the LONGEST cached
  chain anywhere in the fleet and either routes to its holder or PULLS
  the blocks cross-replica (checksummed export/graft — a stale entry or
  corrupt transfer degrades to recompute, never wrong KV). And with
  ``RouterConfig.prefill_replicas`` set, long prompts run their chunked
  prefill on a dedicated PREFILL-ONLY pool, then hand off to a decode
  replica through the live-migration adopt path (``recomputed_tokens ==
  0``) — decode TPOT stops paying for other requests' prefill bubbles.
  Both collapse to the unified path when disabled, empty, or failing.

* **Autoscale actuation + rolling restarts.** :meth:`autoscale` consumes
  the same :func:`~.supervisor.autoscale_signal` telemetry the PR-7
  supervisor emits — aggregated fleet-wide — to SPAWN a replica on
  scale-up (optionally also writing the elastic launcher's
  ``--elastic_rejoin_file``) and DRAIN the least-loaded one on scale-in;
  :meth:`poll_rejoin` reads the same file format back so an external
  autoscaler can drive the fleet. :meth:`start_rolling_restart` drains
  one replica at a time while the router shifts traffic — in-flight work
  finishes (or fails over), the replica rebuilds from the shared
  programs, and the roll moves on: a live trace across the roll completes
  with ZERO failed requests.

The router is synchronous and thread-safe like the supervisor;
:class:`ServingServer` drives it from its pump thread unchanged.
"""

from __future__ import annotations

import dataclasses
import random
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ...flags import flag
from ...health import watchdog as _watchdog
from .directory import CacheDirectory
from .journal import RequestJournal
from .paged_cache import prefix_block_chain
from .replica import CircuitBreaker, Replica
from .scheduler import (CANCELLED, FINISHED, QUEUED, TERMINAL_STATES,
                        ServingQueueFull, completes_by_tokens)
from .supervisor import (EngineSupervisor, FAILED, ServingUnavailable,
                         autoscale_signal, install_drain_handler,
                         uninstall_drain_handler)

__all__ = ["ServingRouter", "RouterConfig", "RouterRequest",
           "ROUTER_HEALTH_FIELDS"]

# field -> meaning for ServingRouter.health_snapshot(); docs/OPS.md's
# "Serving fleet" section renders this and the snapshot test pins the live
# payload's keys to it — same contract as engine.HEALTH_SNAPSHOT_FIELDS.
ROUTER_HEALTH_FIELDS = {
    "ok": "at least one replica is alive with a quiet watchdog (the "
          "fleet can still serve)",
    "accepting": "whether a submit() right now could be routed: some "
                 "replica is routable (breaker closed, not draining/"
                 "retiring, queue open) and the router itself is not "
                 "draining",
    "queued": "fleet-wide queued requests (sum over replicas)",
    "queue_limit": "fleet-wide admission bound (sum over replicas)",
    "live_slots": "fleet-wide occupied decode slots",
    "max_slots": "fleet-wide slot capacity",
    "retry_after_s": "suggested client backoff: the minimum "
                     "retirement-interval estimate over replicas still "
                     "serving (broken / breaker-open / retiring replicas "
                     "excluded — their idle schedulers promise capacity "
                     "that no longer takes traffic)",
    "counters": "router lifetime totals: routed / sticky_hits / "
                "failovers / failover_tokens / hedges / hedge_wins / "
                "hedges_cancelled / probe_failures / breaker_opens / "
                "replica_restarts / rolls_completed / migrations + "
                "migration_tokens (requests moved LIVE with their KV "
                "blocks during a drain/roll/scale-in — the tokens never "
                "recompute; ISSUE 16) / migration_fallbacks (exports "
                "that no replica could adopt; they ride the resubmit/"
                "recompute path instead) / directory_hits (submits "
                "routed to the replica the fleet cache directory says "
                "holds the longest prefix chain; ISSUE 17) / "
                "cache_pulls + pulled_blocks (cross-replica chain "
                "pulls that landed at least one checksummed block on "
                "the target) / pull_fallbacks (pulls that found "
                "nothing to move — stale entry, layout mismatch or "
                "checksum failure; the submit recomputes) / "
                "prefill_routed (long prompts classified onto the "
                "disaggregated prefill pool) / prefill_handoffs "
                "(prefill->decode adoptions, recomputed_tokens == 0) / "
                "handoff_fallbacks (handoffs that collapsed to "
                "decoding in place on the prefill replica) / "
                "adapter_affinity_hits (adapter submits routed to a "
                "replica already holding the adapter device-resident; "
                "ISSUE 19) / adapter_loads (adapter submits that had "
                "to fault the adapter in somewhere — a thrashing "
                "signal when it grows with steady traffic) / "
                "completed / failed "
                "(failed MUST stay 0 across a rolling restart)",
    "directory": "fleet cache directory snapshot: entries / adds / "
                 "drops / evicted ({'enabled': false} when "
                 "RouterConfig.fleet_cache is off)",
    "replicas": "per-replica rows: accepting / role (decode|prefill) / "
                "broken / draining / "
                "retiring / generation / restarts / depth / breaker "
                "(state, consecutive_failures, threshold, cooldown_s, "
                "opens, half_open_probes, reclosures)",
    "fleet": "size / routable / open_breakers / draining / retiring / "
             "prefill (disaggregated prefill-pool size) — the "
             "degraded-then-recovered story /readyz tells",
    "roll": "rolling-restart progress: active / target / pending / "
            "restarted",
    "autoscale": "fleet-aggregated autoscale_signal() record (peeked — "
                 "reading it never consumes the shed delta)",
    "watchdog": "global hang-watchdog state (installed / fired / "
                "timeout_s) — process-wide, shared by every replica",
    "audit": "InvariantAuditor verdict (audit.py AUDIT_CHECKS: block-"
             "pool partition conservation, zero leaks at idle, terminal-"
             "state consistency, per-tenant accounting closure, "
             "monotonic counters) run fleet-wide inside this snapshot "
             "when FLAGS_serving_audit is on; {'enabled': false} "
             "otherwise — the checks walk every block map, a cost a hot "
             "loop only pays when asked to",
    "supervisor": "single-supervisor compatibility summary so /readyz "
                  "serves a router unchanged: draining / broken (ALL "
                  "replicas broken) / restarts (fleet total) / "
                  "restart_budget (fleet total)",
}


@dataclasses.dataclass
class RouterConfig:
    """Fleet knobs; ``None`` fields resolve from ``FLAGS_serving_router_*``
    (flags.py) at construction, the same contract as ServingConfig."""

    replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    breaker_threshold: Optional[int] = None
    breaker_cooldown_s: Optional[float] = None
    hedge_ttft_mult: Optional[float] = None   # 0 = hedging off
    ttft_slo_s: Optional[float] = None        # base for the hedge delay
    affinity: bool = True                     # prefix/tenant stickiness
    # live KV migration (ISSUE 16): drain/roll/scale-in moves in-flight
    # requests to an adoptive replica WITH their computed blocks instead
    # of recomputing; None resolves FLAGS_serving_migrate
    migrate: Optional[bool] = None
    # disaggregated prefill + fleet cache directory (ISSUE 17): a pool
    # of prefill-only replicas long prompts are classified onto (0 =
    # unified serving), the prompt length (tokens) at which a request
    # counts as long, and the fleet-wide prefix-chain directory that
    # replaces the first-block affinity map; None resolves the
    # FLAGS_serving_* flags of the same names
    prefill_replicas: Optional[int] = None
    prefill_len_threshold: Optional[int] = None
    fleet_cache: Optional[bool] = None
    seed: int = 0                             # P2C sampling RNG
    # successful health probes are cached this long: 0 (default) probes
    # every candidate on every submit — the spec'd behavior, and what a
    # few replicas can afford; a large fleet under heavy traffic sets a
    # small TTL so routing stops paying N full snapshots per request.
    # Probe FAILURES are never cached (breaker charging stays exact).
    probe_ttl_s: float = 0.0

    def __post_init__(self):
        if self.replicas is None:
            self.replicas = int(flag("FLAGS_serving_router_replicas"))
        if self.max_replicas is None:
            self.max_replicas = int(
                flag("FLAGS_serving_router_max_replicas"))
        if self.breaker_threshold is None:
            self.breaker_threshold = int(
                flag("FLAGS_serving_router_breaker_threshold"))
        if self.breaker_cooldown_s is None:
            self.breaker_cooldown_s = float(
                flag("FLAGS_serving_router_breaker_cooldown_s"))
        if self.hedge_ttft_mult is None:
            self.hedge_ttft_mult = float(
                flag("FLAGS_serving_router_hedge_ttft_mult"))
        if self.ttft_slo_s is None:
            self.ttft_slo_s = float(flag("FLAGS_serving_ttft_slo_s"))
        if self.migrate is None:
            self.migrate = bool(flag("FLAGS_serving_migrate"))
        if self.prefill_replicas is None:
            self.prefill_replicas = int(
                flag("FLAGS_serving_router_prefill_replicas"))
        if self.prefill_len_threshold is None:
            self.prefill_len_threshold = int(
                flag("FLAGS_serving_prefill_len_threshold"))
        if self.fleet_cache is None:
            self.fleet_cache = bool(flag("FLAGS_serving_fleet_cache"))
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1 (got {self.replicas})")
        if self.prefill_replicas < 0:
            raise ValueError("prefill_replicas must be >= 0 "
                             f"(got {self.prefill_replicas})")
        # the ceiling governs DECODE autoscale headroom; the prefill pool
        # is fixed-size and must not eat it
        self.max_replicas = max(self.max_replicas, self.replicas) \
            + self.prefill_replicas

    @property
    def hedge_after_s(self) -> Optional[float]:
        """Seconds without a first token before a hedge fires; None =
        hedging disabled (either knob at 0 disables)."""
        if self.hedge_ttft_mult and self.ttft_slo_s:
            return self.hedge_ttft_mult * self.ttft_slo_s
        return None


@dataclasses.dataclass
class RouterRequest:
    """The router's replica-independent view of one request: enough to
    fail it over to any replica (prompt + RESOLVED knobs) plus the tokens
    already delivered to the client — a failover resumes after them,
    never repeating one (the same contract TrackedRequest gives one
    supervisor, lifted fleet-wide)."""

    frid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    tenant: Optional[str]
    priority: int
    deadline: Optional[float]
    # RESOLVED sampling knobs (ISSUE 11): a failover/hedge replays them
    # verbatim — per-token-index PRNG keys keep the sampled stream
    # bit-identical across replicas, so hedged copies stay
    # interchangeable and failover never forks a stream
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    adapter_id: Optional[str] = None  # LoRA adapter (ISSUE 19): failover/
    #                                   hedge copies re-select it, so the
    #                                   copies stay interchangeable
    replica: int = -1                 # current primary replica rid
    srid: int = -1                    # supervisor rid on that replica
    jid: int = -1                     # journal record id (ISSUE 18);
    #                                    journal-global across the fleet
    affinity_key: Optional[int] = None
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    state: str = QUEUED
    finish: Optional[Dict[str, Any]] = None
    failovers: int = 0
    # disaggregated prefill (ISSUE 17): True while the request runs on a
    # prefill-only replica; cleared on handoff to a decode replica (or
    # on the collapse-to-unified fallbacks). Hedging skips staged
    # requests — the handoff IS their second-replica path.
    prefill_stage: bool = False
    hedge: Optional[Tuple[int, int]] = None   # (replica rid, srid)
    hedged: bool = False              # a hedge was ever placed
    client_cancelled: bool = False

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES or self.state == FAILED

    @property
    def finished_by_tokens(self) -> bool:
        return completes_by_tokens(self.tokens, self.max_new_tokens,
                                   self.eos_token_id)


class ServingRouter:
    """Health-aware router over N in-process supervised replicas. Request
    ids returned by :meth:`submit` are ROUTER ids (frids) — stable across
    replica failovers and restarts (supervisor rids are not)."""

    # affinity entries retained; hostile traffic minting a fresh prefix
    # per request must not grow host memory unboundedly (same bound
    # philosophy as Scheduler.MAX_TENANTS) — oldest-inserted evict first
    MAX_AFFINITY = 4096

    def __init__(self, params, model_config, serving_config=None,
                 gen_config=None, router_config: Optional[RouterConfig]
                 = None, replicas: Optional[int] = None, programs=None,
                 journal="unset", embed_model=None):
        from .engine import ServingConfig
        self.config = router_config or RouterConfig(replicas=replicas)
        if replicas is not None and router_config is not None:
            raise ValueError("pass replicas= or router_config=, not both")
        self._params = params
        self._model_config = model_config
        self._serving_config = serving_config or ServingConfig()
        self._gen_config = gen_config
        self._embed_model = embed_model
        # multi-adapter LoRA (ISSUE 19): the fleet-wide adapter registry
        # — register_adapter fans out to every replica, and every spawn/
        # rebuild re-registers from here so the whole fleet always serves
        # the same adapter set
        self._adapter_registry: Dict[str, Any] = {}
        self._programs = programs
        self._lock = threading.RLock()
        self._rng = random.Random(self.config.seed)
        self._replicas: Dict[int, Replica] = {}
        self._routes: Dict[int, Dict[int, int]] = {}  # rid -> {srid: frid}
        self._reqs: Dict[int, RouterRequest] = {}
        # non-terminal subset of _reqs: pending/hedge scans stay O(live),
        # not O(every request ever routed)
        self._active: Dict[int, RouterRequest] = {}
        # terminal-record retention bound (same philosophy as
        # Scheduler.keep_finished): the most requests that can be in
        # flight fleet-wide, so one drain/roll can always collect its
        # results afterwards, while a long-lived router cannot retain
        # every prompt it ever served
        self._keep_finished = max(64, (
            int(self._serving_config.queue_depth)
            + 2 * int(self._serving_config.max_slots))
            * int(self.config.max_replicas))
        self._affinity: Dict[int, int] = {}           # key -> replica rid
        self._next_frid = 0
        self._next_replica_rid = 0
        self._drain_requested = False
        self.draining = False
        self.closed = False
        self._prev_sigterm = None
        self._roll: Optional[Dict[str, Any]] = None
        self._auditor = None          # lazy InvariantAuditor (audit())
        self._shed_accum = 0       # monotonic fleet-lifetime shed total
        self._last_shed = 0        # baseline autoscale_signal() consumed
        # lifetime contributions of replicas since rebuilt/removed, so
        # the snapshot's "lifetime totals" never go backwards when a
        # roll resets a supervisor or scale-in drops a replica
        self._opens_retired = 0
        self._restarts_retired = 0
        # counters (ROUTER_HEALTH_FIELDS["counters"])
        self.routed = 0
        self.sticky_hits = 0
        self.failovers = 0
        self.failover_tokens = 0
        self.hedges = 0
        self.hedge_wins = 0            # the hedge copy beat the primary
        self.hedges_cancelled = 0      # losing copies cancelled (KV freed)
        self.probe_failures = 0
        self.replica_restarts = 0      # rolling-restart rebuilds
        self.rolls_completed = 0
        self.migrations = 0            # live KV migrations completed
        self.migration_tokens = 0      # tokens that skipped recompute
        self.migration_fallbacks = 0   # exports no replica could adopt
        self.directory_hits = 0        # routed to the fleet-cache holder
        self.cache_pulls = 0           # cross-replica pulls that landed
        self.pulled_blocks = 0         # blocks grafted by those pulls
        self.pull_fallbacks = 0        # pulls that degraded to recompute
        self.prefill_routed = 0        # long prompts onto the prefill pool
        self.prefill_handoffs = 0      # prefill->decode adoptions (0 rcmp)
        self.handoff_fallbacks = 0     # collapsed to decoding in place
        self.adapter_affinity_hits = 0  # routed to a replica already
        #                                 holding the adapter resident
        self.adapter_loads = 0         # routed where the adapter was NOT
        #                                resident (the pick faults it in)
        self.completed = 0
        self.failed = 0                # router-terminal FAILED (no replica)
        self.cold_recovered = 0        # requests resubmitted by cold_start
        # fleet-wide prefix-chain directory (ISSUE 17): fed by the
        # BlockManager/offload-tier callbacks _wire_directory installs
        # on every replica; None = legacy first-block affinity only
        self._directory: Optional[CacheDirectory] = (
            CacheDirectory() if self.config.fleet_cache else None)
        # durable serving (ISSUE 18): the WHOLE fleet shares ONE journal
        # (jids are journal-global), resolved here and passed explicitly
        # to every supervisor — they must never self-resolve the flag
        # into N competing journals on the same directory.
        if isinstance(journal, str) and journal == "unset":
            jdir = str(flag("FLAGS_serving_journal_dir", ""))
            journal = RequestJournal(jdir) if jdir else None
        self._journal = journal
        for _ in range(self.config.replicas):
            self.spawn_replica()
        for _ in range(self.config.prefill_replicas):
            self.spawn_replica(role="prefill")

    # ---- fleet membership --------------------------------------------------

    def _build_supervisor(self) -> EngineSupervisor:
        sup = EngineSupervisor(self._params, self._model_config,
                               self._serving_config, self._gen_config,
                               programs=self._programs,
                               journal=self._journal,
                               embed_model=self._embed_model)
        # EVERY replica shares the first one's compiled programs: a fleet
        # costs one compile total, and the flat trace counter proves it
        self._programs = sup.engine.programs
        for name, aparams in self._adapter_registry.items():
            sup.register_adapter(name, aparams)
        return sup

    # ---- multi-adapter LoRA + embeddings (ISSUE 19) --------------------------

    def register_adapter(self, name: str, adapter_params) -> None:
        """Register one LoRA adapter FLEET-WIDE: every current replica
        (decode and prefill pools alike) registers it now, and every
        future spawn/rebuild re-registers it from the router's registry
        — a request carrying ``adapter_id`` can then land anywhere a
        failover or hedge takes it."""
        with self._lock:
            for rep in self._replicas.values():
                rep.sup.register_adapter(name, adapter_params)
            self._adapter_registry[str(name)] = adapter_params

    def adapter_registered(self, name: str) -> bool:
        with self._lock:
            return str(name) in self._adapter_registry

    def embed(self, prompts: Sequence, tenant: Optional[str] = None,
              priority: int = 0) -> np.ndarray:
        """Pooled sentence embeddings for ``prompts`` — the prefill-only
        request kind, routed to one healthy replica and pumped to
        completion (embedding batches retire inside the admitting step,
        so this returns after at most a few fleet steps). Returns
        ``[len(prompts), hidden]`` fp32 rows in submission order.
        Embeddings are stateless and unjournaled: a crash mid-batch
        raises and the client simply retries."""
        with self._lock:
            if self._drain_requested or self.draining or self.closed:
                raise ServingUnavailable(
                    "router draining: admissions stopped fleet-wide",
                    reason="draining", retry_after_s=self._retry_after())
            cands = self._candidates()
            if not cands:
                raise ServingUnavailable(
                    "no routable replica for embeddings",
                    reason="no_replica",
                    retry_after_s=self._retry_after())
            rep = (cands[0] if len(cands) == 1
                   else min(self._rng.sample(cands, 2),
                            key=lambda r: r.probe_depth))
            erids = [rep.sup.submit_embedding(p, tenant=tenant,
                                              priority=priority)
                     for p in prompts]
            self.routed += len(erids)
        for _ in range(64):
            with self._lock:
                if all(rep.sup.embedding(e) is not None for e in erids):
                    break
                rep.sup.step()
        with self._lock:
            rows = [rep.sup.embedding(e) for e in erids]
        if any(r is None for r in rows):
            raise RuntimeError("embedding batch did not complete "
                               "(replica crashed mid-batch; retry)")
        return np.stack(rows)

    # ---- durable cold-restart recovery (ISSUE 18) ---------------------------

    @property
    def journal(self) -> Optional[RequestJournal]:
        return self._journal

    @classmethod
    def cold_start(cls, journal_dir: str, params, model_config,
                   serving_config=None, gen_config=None,
                   router_config: Optional[RouterConfig] = None,
                   replicas: Optional[int] = None, programs=None,
                   journal: Optional[RequestJournal] = None,
                   embed_model=None,
                   adapters: Optional[Dict[str, Any]] = None
                   ) -> "ServingRouter":
        """Rebuild the fleet after a FULL process death from the shared
        journal directory: spawn fresh replicas, then for every journal
        record — terminal ones become readable router records; ones
        whose delivered tokens already complete them close FINISHED
        (record it, don't re-run it); every other request resubmits
        bit-exactly from prompt + delivered-so-far under its original
        jid onto a healthy replica. Greedy and seeded streams resume
        bit-identical to an uninterrupted run and no delivered token is
        ever re-emitted — the exactly-once ledger is primed from the
        journal. Idempotent: dying again during recovery and cold-
        starting once more replays to the same state."""
        j = journal if journal is not None else RequestJournal(journal_dir)
        router = cls(params, model_config, serving_config, gen_config,
                     router_config, replicas=replicas, programs=programs,
                     journal=j, embed_model=embed_model)
        for name, aparams in (adapters or {}).items():
            router.register_adapter(name, aparams)
        router._restore_from_journal()
        return router

    def _restore_from_journal(self) -> None:
        """Turn the journal mirror into router records + replica
        resubmissions, in jid (original submission) order."""
        j = self._journal
        if j is None:
            return
        with self._lock:
            now = time.time()
            for jid in sorted(j.records):
                rec = j.records[jid]
                req = RouterRequest(
                    frid=self._next_frid, prompt=rec.prompt_array(),
                    max_new_tokens=rec.max_new_tokens,
                    eos_token_id=rec.eos_token_id, tenant=rec.tenant,
                    priority=rec.priority, deadline=rec.deadline,
                    temperature=rec.temperature, top_k=rec.top_k,
                    top_p=rec.top_p, seed=rec.seed,
                    adapter_id=rec.adapter_id, jid=jid,
                    submit_t=now)
                req.tokens = [int(t) for t in rec.tokens]
                self._next_frid += 1
                self._reqs[req.frid] = req
                if rec.terminal:
                    req.state = rec.state
                    req.finish = {"state": rec.state,
                                  "tokens": len(req.tokens),
                                  "recovered": True}
                    continue
                if req.finished_by_tokens:
                    # died after its last delivered token but before the
                    # terminal event landed: it IS complete
                    req.state = FINISHED
                    req.finish = {"state": FINISHED,
                                  "tokens": len(req.tokens),
                                  "recovered": True,
                                  "finished_by_tokens": True}
                    self.completed += 1
                    j.log_terminal(jid, FINISHED)
                    continue
                placed = False
                for rep in self._candidates(now=now) or \
                        [r for r in self._replicas.values()
                         if r.adoptable() and r.role == "decode"]:
                    try:
                        srid = rep.sup.resubmit(
                            req.prompt, req.tokens,
                            max_new_tokens=req.max_new_tokens,
                            eos_token_id=req.eos_token_id,
                            deadline=req.deadline, tenant=req.tenant,
                            priority=req.priority,
                            temperature=req.temperature,
                            top_k=req.top_k, top_p=req.top_p,
                            seed=req.seed, jid=jid,
                            adapter_id=req.adapter_id)
                    except Exception:  # noqa: BLE001 — raced a drain
                        continue
                    self._routes[rep.rid][srid] = req.frid
                    req.replica, req.srid = rep.rid, srid
                    self._active[req.frid] = req
                    self.cold_recovered += 1
                    placed = True
                    break
                if not placed:
                    req.state = FAILED
                    req.finish = {"state": FAILED,
                                  "tokens": len(req.tokens),
                                  "reason": "no_replica",
                                  "recovered": True}
                    self.failed += 1
                    j.log_terminal(jid, FAILED)
            j.flush()

    def _journal_router_end(self, req: RouterRequest, state: str) -> None:
        """Journal a router-level terminal no engine can log (the owning
        replica is gone): FAILED with no replica left, or finished-by-
        tokens resolved during failover."""
        if self._journal is not None and req.jid >= 0:
            self._journal.log_terminal(req.jid, state)
            self._journal.flush()

    def spawn_replica(self, role: str = "decode") -> Optional[int]:
        """Add one replica (autoscale scale-up / construction). Returns
        its rid, or None at the ``max_replicas`` ceiling.
        ``role="prefill"`` adds to the disaggregated prefill pool."""
        with self._lock:
            if len(self._replicas) >= self.config.max_replicas:
                return None
            rid = self._next_replica_rid
            self._next_replica_rid += 1
            rep = Replica(rid, self._build_supervisor(),
                          CircuitBreaker(self.config.breaker_threshold,
                                         self.config.breaker_cooldown_s),
                          role=role)
            self._replicas[rid] = rep
            self._routes[rid] = {}
            self._wire_directory(rep)
            return rid

    def _wire_directory(self, rep: Replica) -> None:
        """Point the replica's CURRENT engine at the fleet cache
        directory: every prefix-chain key the BlockManager registers
        appears under this rid, every removal path — device
        unregistration without a surviving host-tier copy, tier
        eviction/discard/verified-take — drops it. Re-run after every
        engine rebuild (crash recovery, rolling restart): the callbacks
        die with the old BlockManager, and the fresh pool starts
        empty."""
        if self._directory is None:
            return
        d, rid = self._directory, rep.rid
        try:
            cache = rep.sup.engine.cache
        except Exception:              # noqa: BLE001 — mid-crash rebuild
            return
        cache.manager.notify_register = lambda key: d.add(rid, key)
        cache.manager.notify_unregister = lambda key: d.drop(rid, key)
        if cache.offload is not None:
            cache.offload.on_drop = lambda key: d.drop(rid, key)

    def drain_replica(self, rid: int) -> None:
        """Scale-in: stop routing to the replica, migrate its in-flight
        work out live (KV blocks and all, when ``RouterConfig.migrate``)
        and let whatever stays finish in place (step() keeps pumping it);
        remove it once empty."""
        with self._lock:
            rep = self._replicas[rid]
            rep.retiring = True
            rep.sup.request_drain()
            self._migrate(rep, time.time())

    def _finalize_retiring(self) -> None:
        for rid in [r for r, rep in self._replicas.items() if rep.retiring]:
            rep = self._replicas[rid]
            if rep.sup.pending or self._routes.get(rid):
                continue
            rep.sup.drain(0)              # close out; nothing in flight
            self._opens_retired += rep.breaker.opens
            self._restarts_retired += rep.sup.restarts
            del self._replicas[rid]
            self._routes.pop(rid, None)
            self._affinity = {k: v for k, v in self._affinity.items()
                              if v != rid}
            if self._directory is not None:
                # scale-in: its cached chains left with it
                self._directory.drop_replica(rid)

    @property
    def replicas(self) -> List[int]:
        with self._lock:
            return list(self._replicas)

    # ---- routing -----------------------------------------------------------

    def _probe(self, rep: Replica, now: float) -> Optional[Dict[str, Any]]:
        """One health probe (the in-process /readyz + health_snapshot):
        a raising probe charges the replica's breaker. Successes are
        cached for ``RouterConfig.probe_ttl_s`` (default 0 = always
        probe); failures never are."""
        ttl = self.config.probe_ttl_s
        if ttl > 0 and rep.probe_cache is not None \
                and now - rep.probe_t < ttl:
            return rep.probe_cache
        try:
            snap = rep.probe()
        except Exception:              # noqa: BLE001 — wedged ops surface
            self.probe_failures += 1
            rep.breaker.record_failure(now)
            rep.probe_cache = None
            return None
        rep.probe_cache, rep.probe_t = snap, now
        return snap

    def _half_open_probe(self, rep: Replica, now: float) -> None:
        rep.breaker.probe_started()
        rep.probe_cache = None        # the decision needs a REAL probe:
        #                               a cached pre-failure snapshot
        #                               must not close the breaker
        snap = self._probe(rep, now)
        if snap is None:
            return                     # record_failure already re-opened
        if rep.sup.broken:
            rep.breaker.trip(now)      # still broken: stay open
            return
        rep.breaker.record_success()   # rejoin the candidate set

    def _candidates(self, exclude: Set[int] = frozenset(),
                    now: Optional[float] = None,
                    role: str = "decode") -> List[Replica]:
        now = time.time() if now is None else now
        out = []
        for rep in self._replicas.values():
            if rep.rid in exclude or rep.role != role:
                continue
            if rep.breaker.ready_to_probe(now):
                self._half_open_probe(rep, now)
            if not rep.breaker.allow() or rep.retiring or rep.draining:
                continue
            snap = self._probe(rep, now)
            if snap is None or not snap.get("accepting"):
                continue
            # the probe already carries the load signal — stash it so
            # _pick's two-choice comparison reads it instead of taking
            # the supervisor+engine locks again per sampled replica
            rep.probe_depth = int(snap["queued"]) + int(snap["live_slots"])
            out.append(rep)
        return out

    def _retry_after(self) -> Optional[float]:
        """Backoff hint: the minimum retirement-interval estimate over
        replicas still serving (or about to again) — a broken,
        breaker-open or retiring replica's fresh-but-idle scheduler must
        not promise capacity that no longer takes traffic.

        With a disaggregated prefill pool the DECODE minimum alone is
        the wrong hint for a shed long prompt: an idle decode fleet
        promises sub-second retries while every prefill replica is
        backlogged. When the prefill pool exists and none of it is
        routable, the pool's own estimate — already scaled by
        ``Scheduler.prefill_queue_depth`` — is the binding one."""
        decode_vals, prefill_vals = [], []
        prefill_routable = False
        for rep in self._replicas.values():
            if rep.sup.broken or not rep.breaker.allow() or rep.retiring:
                continue
            try:
                v = rep.sup.engine._sched.retry_after_s()
            except Exception:          # noqa: BLE001
                continue
            if rep.role == "prefill":
                prefill_vals.append(v)
                prefill_routable = prefill_routable or rep.routable()
            else:
                decode_vals.append(v)
        if prefill_vals and not prefill_routable:
            return min(prefill_vals)   # the saturated pool binds
        return min(decode_vals) if decode_vals else (
            min(prefill_vals) if prefill_vals else None)

    def _depth(self, rep: Replica) -> int:
        try:
            return rep.depth()
        except Exception:              # noqa: BLE001
            return 1 << 30

    def _affinity_key(self, prompt: np.ndarray,
                      tenant: Optional[str]) -> Optional[int]:
        """Stickiness key: the tenant plus the prompt's LEADING FULL
        BLOCK of token ids — the exact unit the prefix cache registers,
        so traffic sharing a system-prompt prefix lands where its cached
        blocks live."""
        if not self.config.affinity:
            return None
        bs = self.decode_config.block_size
        if prompt.shape[0] < bs:
            return None
        return hash((tenant, prompt[:bs].tobytes()))

    def _prompt_chain(self, prompt: np.ndarray,
                      adapter_id: Optional[str] = None
                      ) -> List[Tuple[int, tuple]]:
        """The prompt's full chained prefix keys — the directory lookup
        unit (every FULL block, not just the leading one: two prompts
        sharing three blocks route to the same holder even when their
        first blocks are ubiquitous). ``adapter_id`` seeds the chain
        exactly like the engine's admit does (ISSUE 19) — adapter KV
        lives in its own key space, so directory hits for adapter
        traffic resolve to blocks the target admit can actually map.
        Empty when the directory is off or the prompt spans no full
        block."""
        if self._directory is None:
            return []
        bs = self.decode_config.block_size
        if prompt.shape[0] < bs:
            return []
        return list(prefix_block_chain(prompt, bs, prompt.shape[0],
                                       namespace=adapter_id))

    def _pull_chain(self, holder_rid: int, target: Replica,
                    chain: List[Tuple[int, tuple]]) -> int:
        """Move a cached chain's blocks cross-replica: serialize on the
        holder (device read or host-tier peek, per-leaf CRC32 stamped),
        graft into the target's pool (CRC re-verified, registered as
        ordinary refcount-0 cached blocks). Any failure — stale
        directory entry, layout mismatch, checksum mismatch, dry pool —
        lands as ``pull_fallbacks`` and the submit recomputes: a pull
        can cost time, never correctness. Returns blocks grafted."""
        src = self._replicas.get(holder_rid)
        if src is None or not chain:
            return 0
        try:
            payload = src.sup.export_chain(chain)
        except Exception:              # noqa: BLE001 — sick holder
            payload = None
        if payload is None:
            # stale-missing entry: the holder evicted since the lookup
            self.pull_fallbacks += 1
            if self._directory is not None:
                for k, _ in chain:
                    self._directory.drop(holder_rid, k)
            return 0
        try:
            res = target.sup.graft_chain(payload)
        except Exception:              # noqa: BLE001 — AdoptError/drain
            self.pull_fallbacks += 1
            return 0
        got = int(res.get("grafted", 0))
        self.pulled_blocks += got
        if got or res.get("present"):
            self.cache_pulls += 1
        else:
            self.pull_fallbacks += 1
        return got

    def _pick(self, cands: List[Replica],
              key: Optional[int]) -> Replica:
        if key is not None:
            rid = self._affinity.get(key)
            if rid is not None:
                rep = self._replicas.get(rid)
                if rep is not None and rep in cands:
                    self.sticky_hits += 1
                    return rep
        if len(cands) == 1:
            return cands[0]
        # power-of-two-choices on the depth the candidacy probe measured
        # (same lock-held pass, so it cannot be stale)
        a, b = self._rng.sample(cands, 2)
        return a if a.probe_depth <= b.probe_depth else b

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = "unset",
               timeout_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None, priority: int = 0,
               temperature="unset", top_k="unset", top_p="unset",
               seed="unset", replica: Optional[int] = None,
               adapter_id: Optional[str] = None) -> int:
        """Route one prompt to a healthy replica; returns the ROUTER
        request id. ``replica`` pins the pick (an ops/canary hook — the
        pinned replica must still be routable). Raises
        :class:`ServingUnavailable` when no replica can take traffic and
        passes the last replica's :class:`ServingQueueFull` through when
        the whole fleet is shedding."""
        with self._lock:
            if self._drain_requested or self.draining or self.closed:
                raise ServingUnavailable(
                    "router draining: admissions stopped fleet-wide",
                    reason="draining", retry_after_s=self._retry_after())
            if adapter_id is not None \
                    and str(adapter_id) not in self._adapter_registry:
                raise ValueError(
                    f"adapter {adapter_id!r} is not registered with this "
                    f"router (register_adapter first; registered: "
                    f"{sorted(self._adapter_registry)})")
            now = time.time()
            cands = self._candidates(now=now)
            if not cands:
                # healthy replicas whose only problem is a FULL admission
                # queue are still submit targets: the attempt below sheds
                # with the engine's structured ServingQueueFull (the 429
                # a single supervisor gives), not a misleading
                # "broken/circuit-broken" 503 for plain overload
                cands = [rep for rep in self._replicas.values()
                         if rep.adoptable() and rep.role == "decode"]
            if replica is not None:
                # an ops/canary pin may name a prefill replica too (the
                # bench's island-cache baseline pins placement directly)
                cands = [r for r in cands
                         + self._candidates(now=now, role="prefill")
                         if r.rid == replica]
            if not cands:
                raise ServingUnavailable(
                    f"no routable replica ({len(self._replicas)} in the "
                    f"fleet: broken, draining, or circuit-broken)",
                    reason="no_replica",
                    retry_after_s=self._retry_after())
            p = np.asarray(prompt, np.int32).reshape(-1)
            key = self._affinity_key(p, tenant)
            chain = self._prompt_chain(
                p, None if adapter_id is None else str(adapter_id))
            holder_rid, depth = (None, 0)
            if chain and self._directory is not None:
                holder_rid, depth = self._directory.longest(
                    [k for k, _ in chain])
            pick = None
            if holder_rid is not None and replica is None:
                # fleet cache hit: the replica holding the longest cached
                # chain takes the request when it has headroom — the
                # admit() there maps depth*block_size tokens, recompute 0
                hrep = self._replicas.get(holder_rid)
                if hrep is not None and hrep.role == "decode" \
                        and hrep in cands:
                    pick = hrep
                    self.directory_hits += 1
                    self.sticky_hits += 1
            prefill_cands: List[Replica] = []
            if pick is None and replica is None \
                    and self.config.prefill_replicas > 0 \
                    and self.config.prefill_len_threshold > 0 \
                    and p.shape[0] >= self.config.prefill_len_threshold:
                # disaggregated prefill: a long prompt runs its chunked
                # prefill on the dedicated pool, then hands the chain to
                # a decode replica via the adopt path; an empty/draining
                # pool falls through to the unified path below
                prefill_cands = self._candidates(now=now, role="prefill")
                if prefill_cands:
                    pick = (prefill_cands[0] if len(prefill_cands) == 1
                            else min(self._rng.sample(prefill_cands, 2),
                                     key=lambda r: r.probe_depth))
            if pick is None and adapter_id is not None and cands:
                # adapter affinity: a replica already holding the adapter
                # RESIDENT serves it without an H2D load; with none, the
                # P2C pick below faults it in (counted — the ops signal
                # for an adapter set that thrashes the pools)
                resident = [r for r in cands
                            if r.sup.adapter_resident(adapter_id)]
                if resident:
                    pick = (resident[0] if len(resident) == 1
                            else min(self._rng.sample(resident, 2),
                                     key=lambda r: r.probe_depth))
                    self.adapter_affinity_hits += 1
                else:
                    self.adapter_loads += 1
            if pick is None:
                pick = self._pick(cands, key)
            if holder_rid is not None and chain \
                    and pick.rid != holder_rid:
                # the chain lives elsewhere: pull its blocks into the
                # pick's prefix cache before admitting — checksummed at
                # both ends, and any failure just means recompute
                self._pull_chain(holder_rid, pick, chain[:depth])
            last_exc: Optional[Exception] = None
            for rep in [pick] + [c for c in prefill_cands + cands
                                 if c is not pick]:
                try:
                    srid = rep.sup.submit(
                        p, max_new_tokens=max_new_tokens,
                        eos_token_id=eos_token_id, timeout_s=timeout_s,
                        deadline_s=deadline_s, tenant=tenant,
                        priority=priority, temperature=temperature,
                        top_k=top_k, top_p=top_p, seed=seed,
                        adapter_id=adapter_id)
                    rep.breaker.record_success()
                    break
                except ServingQueueFull as e:   # full: try the next pick
                    last_exc = e
                except ServingUnavailable as e:  # raced a drain/crash
                    rep.breaker.record_failure(now)
                    last_exc = e
            else:
                raise last_exc
            rec = rep.sup._reqs[srid]     # the RESOLVED request record
            req = RouterRequest(
                frid=self._next_frid, prompt=rec.prompt,
                max_new_tokens=rec.max_new_tokens,
                eos_token_id=rec.eos_token_id, tenant=rec.tenant,
                priority=rec.priority, deadline=rec.deadline,
                temperature=rec.temperature, top_k=rec.top_k,
                top_p=rec.top_p, seed=rec.seed,
                adapter_id=rec.adapter_id,
                replica=rep.rid, srid=srid, jid=rec.jid,
                affinity_key=key, submit_t=now)
            req.prefill_stage = (rep.role == "prefill")
            if req.prefill_stage:
                self.prefill_routed += 1
            self._next_frid += 1
            self._reqs[req.frid] = req
            self._active[req.frid] = req
            self._routes[rep.rid][srid] = req.frid
            if key is not None and rep.role == "decode":
                self._affinity[key] = rep.rid
            self.routed += 1
            while len(self._affinity) > self.MAX_AFFINITY:
                del self._affinity[next(iter(self._affinity))]
            return req.frid

    def _retire_record(self, req: RouterRequest) -> None:
        """Called on every router-terminal transition: drop the request
        from the active set and evict the oldest terminal records past
        the retention bound (results of recent work stay readable via
        :meth:`request`/:meth:`result`)."""
        self._active.pop(req.frid, None)
        excess = len(self._reqs) - len(self._active) - self._keep_finished
        if excess > 0:
            for frid in list(self._reqs):
                if excess <= 0:
                    break
                old = self._reqs[frid]
                if old.terminal and frid != req.frid:
                    del self._reqs[frid]
                    excess -= 1

    def cancel(self, frid: int) -> bool:
        """Cancel by router rid — primary and any hedge copy, idempotent
        like the engine's."""
        with self._lock:
            req = self._reqs.get(frid)
            if req is None or req.terminal:
                return False
            req.client_cancelled = True
            ok = False
            for rid, srid in filter(None, [(req.replica, req.srid),
                                           req.hedge]):
                rep = self._replicas.get(rid)
                if rep is None:
                    continue
                try:
                    ok = rep.sup.cancel(srid) or ok
                except Exception:      # noqa: BLE001 — sick replica
                    pass
            self._sweep(time.time())
            return ok

    # ---- the fleet step loop -----------------------------------------------

    def step(self, max_iters: Optional[int] = None) -> Dict[int, List[int]]:
        """One iteration across every replica. Returns ``{frid: [tokens
        emitted]}`` — exactly-once: a hedged request delivers only its
        winning copy's tokens, a failed-over request resumes after the
        tokens already delivered."""
        with self._lock:
            out: Dict[int, List[int]] = {}
            now = time.time()
            for rep in list(self._replicas.values()):
                # a prefill replica's decode dispatch is bounded to ONE
                # iteration: chunked prefill still advances a full chunk
                # per step (its whole job), but a finished prompt stops
                # right after its first sampled token instead of decoding
                # to completion — the same-step _handoffs() below moves
                # it to a decode replica with zero recompute
                iters = 1 if rep.role == "prefill" else max_iters
                emitted = rep.sup.step(iters) if rep.sup.pending else {}
                self._observe(rep, now)
                routes = self._routes.get(rep.rid, {})
                for srid in sorted(emitted):
                    frid = routes.get(srid)
                    if frid is None:
                        continue                  # cancelled hedge/loser
                    req = self._reqs[frid]
                    if req.terminal:
                        continue
                    if req.hedge is not None:
                        self._resolve_hedge(req, rep.rid, srid)
                        if (req.replica, req.srid) != (rep.rid, srid):
                            continue              # this copy lost
                    if req.first_token_t is None:
                        req.first_token_t = now
                    got = [int(t) for t in emitted[srid]]
                    req.tokens.extend(got)
                    out.setdefault(frid, []).extend(got)
                    if req.jid >= 0:
                        srec = rep.sup._reqs.get(srid)
                        if srec is not None and srec.jid != req.jid:
                            # a promoted hedge copy inherits the logical
                            # request's journal record, rebased to what
                            # the client has ACTUALLY been delivered
                            rep.sup.journal_own(srid, req.jid,
                                                req.tokens)
            self._handoffs(now)
            self._sweep(now)
            self._check_hedges(now)
            self._advance_roll(now)
            self._finalize_retiring()
            return out

    def _handoffs(self, now: float) -> None:
        """Disaggregated prefill stage 2: every staged request that got
        its FIRST token (prefill finished — the prefill replica sampled
        it) moves to a decode replica through the live-migration adopt
        path, KV blocks and all (``recomputed_tokens == 0``). A handoff
        no decode replica can take right now collapses to decoding in
        place on the prefill replica (``handoff_fallbacks``) — the
        unified path, never a lost request."""
        from .engine import AdoptError
        for req in list(self._active.values()):
            if req.terminal or not req.prefill_stage or not req.tokens:
                continue
            rep = self._replicas.get(req.replica)
            if rep is None:
                req.prefill_stage = False     # failover already moved it
                continue
            try:
                payload = rep.sup.export_request(req.srid)
            except Exception:          # noqa: BLE001 — sick origin
                payload = None
            if payload is None:
                # finished inside the prefill replica (tiny max_new /
                # EOS on the first token): the sweep mirrors it; there
                # is nothing left to move
                req.prefill_stage = False
                continue
            moved = False
            for cand in self._candidates(exclude={rep.rid}, now=now):
                try:
                    new_srid = cand.sup.adopt(payload)
                except (AdoptError, ServingUnavailable):
                    continue           # this target can't take the blocks
                except Exception:      # noqa: BLE001 — raced a crash
                    continue
                # pop the route BEFORE cancelling the origin copy so no
                # sweep can double-handle this frid (the _migrate rule)
                self._routes[rep.rid].pop(req.srid, None)
                try:
                    rep.sup.release_migrated(req.srid)
                except Exception:      # noqa: BLE001 — drain reaps it
                    pass
                self._routes[cand.rid][new_srid] = req.frid
                req.replica, req.srid = cand.rid, new_srid
                req.prefill_stage = False
                if req.affinity_key is not None:
                    # shared-prefix traffic follows the blocks
                    self._affinity[req.affinity_key] = cand.rid
                self.prefill_handoffs += 1
                moved = True
                break
            if not moved:
                self.handoff_fallbacks += 1
                req.prefill_stage = False

    def _observe(self, rep: Replica, now: float) -> None:
        """Post-step health accounting: supervisor restarts count as
        breaker failures (a crash LOOP opens the breaker even while the
        restart budget lasts), a broken replica trips it immediately, and
        a newly not-allowed replica is EVACUATED — its requests fail over
        now, not when the budget runs out."""
        if rep.sup.restarts > rep.restarts_seen:
            for _ in range(rep.sup.restarts - rep.restarts_seen):
                rep.breaker.record_failure(now)
            rep.restarts_seen = rep.sup.restarts
            rep.probe_cache = None    # pre-crash snapshot is stale
            if self._directory is not None:
                # the rebuilt engine's pool is EMPTY and its BlockManager
                # is a new object: every directory entry naming this rid
                # died with the old pool, and the callbacks must re-aim
                # at the fresh one — a crash can never leave a
                # stale-authoritative entry behind
                self._directory.drop_replica(rep.rid)
                self._wire_directory(rep)
        if rep.sup.broken and not rep.broken_seen:
            rep.broken_seen = True
            rep.breaker.trip(now)
            rep.probe_cache = None
            if self._directory is not None:
                self._directory.drop_replica(rep.rid)
        if not rep.breaker.allow() and self._routes.get(rep.rid):
            self._evacuate(rep, now)

    def _evacuate(self, rep: Replica, now: float) -> None:
        """Move every non-terminal request off a replica the router no
        longer trusts (breaker open / broken), cancelling the originals
        best-effort so a still-alive-but-sick replica frees its KV."""
        for srid, frid in list(self._routes.get(rep.rid, {}).items()):
            req = self._reqs[frid]
            self._routes[rep.rid].pop(srid, None)
            if req.terminal:
                continue
            is_primary = (req.replica, req.srid) == (rep.rid, srid)
            if not rep.sup.broken:
                if is_primary and req.jid >= 0:
                    # the evacuation cancel must not end the journal
                    # record — the failover below resumes it elsewhere
                    try:
                        rep.sup.disown_journal(srid)
                    except Exception:  # noqa: BLE001
                        pass
                try:
                    rep.sup.cancel(srid)
                except Exception:      # noqa: BLE001
                    pass
            if is_primary:
                self._failover(req, exclude={rep.rid}, now=now)
            else:
                req.hedge = None       # the hedge copy died with its host

    def _migrate(self, rep: Replica, now: float) -> None:
        """Live KV migration (ISSUE 16): move every in-flight PRIMARY
        request off a draining/retiring replica WITH its computed blocks
        — the adoptive replica resumes it mid-stream with
        ``recomputed_tokens == 0`` (the :meth:`EngineSupervisor.adopt`
        contract), bit-identical to staying put. A request no replica
        can adopt (pool full, TP/layout mismatch, mid-crash) stays on
        the origin: the drain window may still finish it, and the
        deadline evacuation falls back to the resubmit/recompute path —
        migration only ever SAVES work, never risks it."""
        if not self.config.migrate:
            return
        from .engine import AdoptError
        for srid, frid in list(self._routes.get(rep.rid, {}).items()):
            req = self._reqs.get(frid)
            if req is None or req.terminal:
                continue
            if (req.replica, req.srid) != (rep.rid, srid):
                continue           # hedge copy: its primary keeps serving
            try:
                payload = rep.sup.export_request(srid)
            except Exception:      # noqa: BLE001 — sick origin
                payload = None
            if payload is None:
                continue           # already finishing inside the drain
            moved = False
            for cand in self._candidates(exclude={rep.rid}, now=now):
                try:
                    new_srid = cand.sup.adopt(payload)
                except (AdoptError, ServingUnavailable):
                    continue       # this target can't take the blocks
                except Exception:  # noqa: BLE001 — raced a crash
                    continue
                # pop the route BEFORE cancelling the origin copy so the
                # drain-cancel sweep can never double-failover this frid
                self._routes[rep.rid].pop(srid, None)
                try:
                    rep.sup.release_migrated(srid)
                except Exception:  # noqa: BLE001 — drain will reap it
                    pass
                self._routes[cand.rid][new_srid] = frid
                req.replica, req.srid = cand.rid, new_srid
                if req.affinity_key is not None:
                    # shared-prefix traffic follows the blocks
                    self._affinity[req.affinity_key] = cand.rid
                self.migrations += 1
                self.migration_tokens += len(req.tokens)
                moved = True
                break
            if not moved:
                self.migration_fallbacks += 1

    def _failover(self, req: RouterRequest, exclude: Set[int],
                  now: float) -> None:
        """Resume one request on a healthy replica from the tokens the
        client already has. An outstanding hedge copy is PROMOTED instead
        of resubmitting (it is already running the same work); with no
        replica available the request goes router-FAILED — partial output
        readable, ``counters.failed`` incremented."""
        req.failovers += 1
        self.failovers += 1
        if req.hedge is not None:
            hrid, hsrid = req.hedge
            req.hedge = None
            if hrid not in exclude and hrid in self._replicas:
                req.replica, req.srid = hrid, hsrid
                self.hedge_wins += 1
                return
        if req.finished_by_tokens:
            req.state = FINISHED
            req.finish = {"state": FINISHED, "tokens": len(req.tokens),
                          "failovers": req.failovers,
                          "finished_by_tokens": True}
            self.completed += 1
            self._journal_router_end(req, FINISHED)
            self._retire_record(req)
            return
        cands = self._candidates(exclude=exclude, now=now)
        if not cands:
            # a replica whose only problem is a FULL admission queue can
            # still ADOPT: resubmit rides the recovery path, which
            # bypasses the queue-depth shed (the work was accepted once,
            # somewhere). Without this fallback, a replica killed at
            # peak saturation (the fleet-replay regime) FAILs its
            # in-flight requests even though healthy replicas remain.
            cands = [rep for rep in self._replicas.values()
                     if rep.rid not in exclude and rep.adoptable()
                     and rep.role == "decode"]
        for rep in cands:
            try:
                srid = rep.sup.resubmit(
                    req.prompt, req.tokens,
                    max_new_tokens=req.max_new_tokens,
                    eos_token_id=req.eos_token_id, deadline=req.deadline,
                    tenant=req.tenant, priority=req.priority,
                    temperature=req.temperature, top_k=req.top_k,
                    top_p=req.top_p, seed=req.seed,
                    jid=req.jid if req.jid >= 0 else None,
                    adapter_id=req.adapter_id)
            except Exception:          # noqa: BLE001 — raced a drain
                continue
            self._routes[rep.rid][srid] = req.frid
            req.replica, req.srid = rep.rid, srid
            # when the crashed supervisor closed the old journal record
            # FAILED, resubmit opened a fresh superseding record — adopt
            # its jid so the ownership hook doesn't chase a dead one
            srec = rep.sup._reqs.get(srid)
            if srec is not None:
                req.jid = srec.jid
            self.failover_tokens += len(req.tokens)
            if req.affinity_key is not None:
                # shared-prefix traffic follows the work to its new home
                self._affinity[req.affinity_key] = rep.rid
            return
        req.state = FAILED
        req.finish = {"state": FAILED, "tokens": len(req.tokens),
                      "failovers": req.failovers, "reason": "no_replica"}
        self.failed += 1
        self._journal_router_end(req, FAILED)
        self._retire_record(req)

    def _sweep(self, now: float) -> None:
        """Mirror replica-terminal transitions into the router records:
        FAILED (budget exhausted) fails over, a drain-cancel out from
        under a live client fails over, everything else lands as the
        request's terminal record — and a terminal primary cancels its
        outstanding hedge copy."""
        for rep in list(self._replicas.values()):
            routes = self._routes.get(rep.rid, {})
            for srid, frid in list(routes.items()):
                rec = rep.sup._reqs.get(srid)
                if rec is None or not rec.terminal:
                    continue
                routes.pop(srid, None)
                req = self._reqs[frid]
                if req.terminal:
                    continue
                is_primary = (req.replica, req.srid) == (rep.rid, srid)
                if not is_primary:
                    # a hedge/stale copy ended on its own (cancelled, or
                    # raced a terminal): nothing to mirror
                    if req.hedge == (rep.rid, srid):
                        req.hedge = None
                    continue
                if rec.state == FAILED:
                    self._failover(req, exclude={rep.rid}, now=now)
                    continue
                if rec.state == CANCELLED and not req.client_cancelled \
                        and not (self._drain_requested or self.draining) \
                        and (rep.draining or rep.retiring or rep.sup.broken):
                    # a drain deadline cancelled it out from under a live
                    # client: the roll's zero-failed contract says move
                    # it, not kill it
                    self._failover(req, exclude={rep.rid}, now=now)
                    continue
                req.tokens = [int(t) for t in rec.tokens]
                req.state = rec.state
                fin = dict(rec.finish or {"state": rec.state,
                                          "tokens": len(rec.tokens)})
                fin.update({"replica": rep.rid,
                            "failovers": req.failovers,
                            "hedged": req.hedged})
                req.finish = fin
                if rec.state == FINISHED:
                    self.completed += 1
                self._cancel_hedge(req)
                self._retire_record(req)

    def _cancel_hedge(self, req: RouterRequest) -> None:
        if req.hedge is None:
            return
        hrid, hsrid = req.hedge
        req.hedge = None
        self._routes.get(hrid, {}).pop(hsrid, None)
        rep = self._replicas.get(hrid)
        if rep is not None:
            try:
                rep.sup.cancel(hsrid)
            except Exception:          # noqa: BLE001
                pass
        self.hedges_cancelled += 1

    def _resolve_hedge(self, req: RouterRequest, rid: int,
                       srid: int) -> None:
        """First token wins: the copy that emitted becomes the primary,
        the other is cancelled through the lifecycle path (KV freed).
        Greedy decode makes the copies bit-identical, so the winner's
        stream IS the stream."""
        if (rid, srid) == (req.replica, req.srid):
            self._cancel_hedge(req)    # primary won
            return
        loser = (req.replica, req.srid)
        lrep = self._replicas.get(loser[0])
        if lrep is not None and req.jid >= 0:
            # the demoted primary must not terminate the journal record
            # its winning copy is about to inherit
            try:
                lrep.sup.disown_journal(loser[1])
            except Exception:          # noqa: BLE001 — sick loser
                pass
        req.replica, req.srid = rid, srid
        req.hedge = loser              # demote, then cancel via the same
        self._cancel_hedge(req)        # path (mapping + engine cancel)
        self.hedge_wins += 1

    def _check_hedges(self, now: float) -> None:
        thresh = self.config.hedge_after_s
        if thresh is None:
            return
        for req in list(self._active.values()):
            if req.terminal or req.tokens or req.hedged \
                    or req.prefill_stage \
                    or now - req.submit_t < thresh:
                continue
            cands = self._candidates(exclude={req.replica}, now=now)
            if not cands:
                continue
            rep = self._pick(cands, None)
            try:
                srid = rep.sup.submit(
                    req.prompt, max_new_tokens=req.max_new_tokens,
                    eos_token_id=req.eos_token_id,
                    deadline_s=req.deadline, tenant=req.tenant,
                    priority=req.priority, temperature=req.temperature,
                    top_k=req.top_k, top_p=req.top_p, seed=req.seed,
                    adapter_id=req.adapter_id)
            except Exception:          # noqa: BLE001 — shed: retry later
                continue
            req.hedge = (rep.rid, srid)
            req.hedged = True
            # the hedge copy is NOT journaled (its emission is not client
            # delivery — the primary's is); on promotion it inherits the
            # primary's record via journal_own
            rep.sup.disown_journal(srid)
            self._routes[rep.rid][srid] = req.frid
            self.hedges += 1

    # ---- rolling restarts ---------------------------------------------------

    def start_rolling_restart(self,
                              drain_deadline_s: Optional[float] = None
                              ) -> None:
        """Begin a one-replica-at-a-time roll: the current target drains
        (admissions shift to the rest of the fleet), its in-flight work
        finishes — or fails over at the deadline — and a fresh supervisor
        is built from the SHARED compiled programs before the roll moves
        on. ``step()`` advances the roll; a live trace served across it
        completes with zero failed requests."""
        with self._lock:
            if self._roll is not None:
                raise RuntimeError("a rolling restart is already active")
            self._roll = {"pending": list(self._replicas), "target": None,
                          "t0": 0.0, "restarted": 0,
                          "deadline_s": (
                              drain_deadline_s if drain_deadline_s
                              is not None
                              else float(flag(
                                  "FLAGS_serving_drain_deadline_s")))}

    @property
    def rolling(self) -> bool:
        return self._roll is not None

    def rolling_restart(self, drain_deadline_s: Optional[float] = None,
                        max_steps: int = 100000) -> int:
        """Blocking convenience: start a roll and pump :meth:`step` until
        it completes. Returns the number of replicas THIS roll restarted
        (an incomplete ``max_steps``-exhausted roll returns fewer than
        the fleet size)."""
        with self._lock:
            before = self.replica_restarts
        self.start_rolling_restart(drain_deadline_s)
        steps = 0
        while self.rolling and steps < max_steps:
            self.step()
            steps += 1
        with self._lock:
            return self.replica_restarts - before

    def _advance_roll(self, now: float) -> None:
        roll = self._roll
        if roll is None:
            return
        if roll["target"] is None:
            if not roll["pending"]:
                self._roll = None
                self.rolls_completed += 1
                return
            roll["pending"] = [rid for rid in roll["pending"]
                               if rid in self._replicas]  # scaled in
            # pick ANY pending replica whose drain the fleet can absorb:
            # a non-routable one (broken / breaker-open) serves no
            # traffic, so rebuilding it never needs cover — insisting on
            # head order would stall the roll forever when the head is
            # the last routable replica and a later entry is the broken
            # one the roll exists to heal
            rid = None
            for cand in roll["pending"]:
                rep = self._replicas[cand]
                if not rep.routable() or \
                        self._candidates(exclude={cand}, now=now):
                    rid = cand
                    break
            if rid is None:
                if len(self._replicas) > 1 or not roll["pending"]:
                    return               # wait for cover to come back
                # a sole healthy replica has nowhere to shift traffic:
                # proceed anyway — a brief admissions outage (structured
                # 503 + retry hint) beats a roll stalled forever
                rid = roll["pending"][0]
            rep = self._replicas[rid]
            roll["pending"].remove(rid)
            roll["target"] = rid
            roll["t0"] = now
            rep.sup.request_drain()
            # live migration empties the target immediately — its KV
            # moves with the requests, so the roll's zero-recompute
            # contract holds even at a 0s drain deadline
            self._migrate(rep, now)
            return
        rid = roll["target"]
        rep = self._replicas.get(rid)
        if rep is None:
            roll["target"] = None
            return
        if rep.sup.pending and now - roll["t0"] < roll["deadline_s"]:
            return                            # still draining; step() pumps
        if rep.sup.pending:
            # deadline: retry live migration first (an earlier fallback
            # may find room now that the fleet drained), then move the
            # stragglers — the same evacuation the breaker path uses
            # (fails primaries over, clears hedge copies so a later
            # failover can't promote a stale srid of the rebuilt
            # supervisor); the close-out drain below then cancels
            # what's left
            self._migrate(rep, now)
            self._evacuate(rep, now)
        report = rep.sup.drain(0)             # close-out + leak check
        fresh = self._build_supervisor()
        old = rep.replace(fresh)
        self._restarts_retired += old.restarts  # lifetime totals survive
        self._routes[rid] = {}
        if self._directory is not None:
            # the rebuilt pool starts empty; re-aim the callbacks at it
            self._directory.drop_replica(rid)
            self._wire_directory(rep)
        roll["restarted"] += 1
        roll["last_report"] = report
        self.replica_restarts += 1
        roll["target"] = None

    # ---- autoscale ----------------------------------------------------------

    def _aggregate(self) -> Dict[str, Any]:
        """Fleet-wide capacity view. The shed total is accumulated
        MONOTONICALLY from per-replica deltas (each against that
        replica's own baseline, re-based when its supervisor is
        rebuilt), so a rolling restart or scale-in — which resets or
        removes a replica's cumulative counter — can never mask new
        shedding from the autoscale delta."""
        agg = {"queued": 0, "queue_limit": 0, "live_slots": 0,
               "max_slots": 0, "retry_after_s": None,
               "counters": {"shed": 0}}
        for rep in self._replicas.values():
            if rep.retiring:
                continue
            try:
                snap = rep.sup.health_snapshot()
            except Exception:          # noqa: BLE001 — skip wedged ops
                continue
            for k in ("queued", "queue_limit", "live_slots", "max_slots"):
                agg[k] += int(snap[k])
            shed = int(snap["counters"]["shed"])
            self._shed_accum += max(0, shed - rep.shed_seen)
            rep.shed_seen = shed
            ra = snap.get("retry_after_s")
            if ra is not None:
                agg["retry_after_s"] = (ra if agg["retry_after_s"] is None
                                        else min(agg["retry_after_s"], ra))
        agg["counters"]["shed"] = self._shed_accum
        return agg

    def autoscale_signal(self, rejoin_file: Optional[str] = None,
                         workers: Optional[int] = None) -> Dict[str, Any]:
        """The fleet-wide scale recommendation (the per-replica signal,
        aggregated), tracking the shed delta between calls. A scale-up
        with ``rejoin_file`` also writes the elastic launcher's signal
        file so an external launcher adds capacity."""
        with self._lock:
            agg = self._aggregate()
            shed = agg["counters"]["shed"]
            delta = max(0, shed - self._last_shed)
            self._last_shed = shed
        sig = autoscale_signal(agg, shed_delta=delta)
        if rejoin_file and sig["action"] == "scale_up":
            from ...distributed.launch.main import write_rejoin_file
            write_rejoin_file(rejoin_file, workers)
            sig["rejoin_file"] = rejoin_file
        return sig

    def autoscale(self, rejoin_file: Optional[str] = None,
                  workers: Optional[int] = None) -> Dict[str, Any]:
        """ACT on the signal: scale-up spawns a replica (sharing the
        compiled programs — no new compile), scale-in drains the
        least-loaded replica (never below one). Returns the signal with
        ``spawned``/``retiring`` annotations."""
        sig = self.autoscale_signal(rejoin_file=rejoin_file,
                                    workers=workers)
        with self._lock:
            if sig["action"] == "scale_up":
                rid = self.spawn_replica()
                if rid is not None:
                    sig["spawned"] = rid
            elif sig["action"] == "scale_in":
                # the floor is one HEALTHY replica: broken/breaker-open
                # replicas neither count toward it nor protect it — with
                # one healthy and one broken replica, min-by-depth would
                # otherwise drain the healthy one (the broken replica
                # reports an un-pickable depth) and self-inflict a total
                # outage
                healthy = [r for r in self._replicas.values()
                           if not r.retiring and not r.sup.broken
                           and r.breaker.allow()
                           and r.role == "decode"]
                if len(healthy) > 1:
                    victim = min(healthy, key=self._depth)
                    self.drain_replica(victim.rid)
                    sig["retiring"] = victim.rid
        return sig

    def poll_rejoin(self, path: str) -> List[int]:
        """Consume an external scale-out signal written in the launcher's
        rejoin-file format (``write_rejoin_file``): spawn up to the
        offered worker count (bounded by ``max_replicas``), then remove
        the file — the same read-and-consume handshake the elastic
        launcher applies between rounds."""
        from ...distributed.launch.main import consume_rejoin_file
        offered = consume_rejoin_file(path)
        spawned: List[int] = []
        with self._lock:
            while offered > 0:
                rid = self.spawn_replica()
                if rid is None:
                    break
                spawned.append(rid)
                offered -= 1
        return spawned

    # ---- client surface (the supervisor contract, fleet-wide) ---------------

    @property
    def pending(self) -> bool:
        with self._lock:
            return bool(self._active)

    def request(self, frid: int) -> RouterRequest:
        with self._lock:
            return self._reqs[frid]

    def result(self, frid: int) -> np.ndarray:
        with self._lock:
            return np.asarray(self._reqs[frid].tokens, np.int32)

    def run(self, prompts: Sequence, max_new_tokens=None,
            eos_token_id="unset") -> List[np.ndarray]:
        """Submit every prompt, drive the fleet to drain, return outputs
        in submission order — the engine ``run()`` contract behind the
        router."""
        n = len(prompts)
        mnt = ([max_new_tokens] * n
               if max_new_tokens is None or np.isscalar(max_new_tokens)
               else list(max_new_tokens))
        frids = [self.submit(p, max_new_tokens=m, eos_token_id=eos_token_id)
                 for p, m in zip(prompts, mnt)]
        while self.pending:
            self.step()
        return [self.result(f) for f in frids]

    @property
    def decode_config(self):
        """The resolved ServingConfig every replica shares (block size
        for affinity keys, decode_chunk for the server pump)."""
        return self._serving_config

    @property
    def decode_chunk(self) -> int:
        return int(self._serving_config.decode_chunk)

    # ---- drain (fleet-wide) --------------------------------------------------

    def request_drain(self) -> None:
        self._drain_requested = True
        with self._lock:
            for rep in self._replicas.values():
                rep.sup.request_drain()

    @property
    def drain_requested(self) -> bool:
        return self._drain_requested

    def install_signal_handler(self, signum: int = signal.SIGTERM):
        """SIGTERM (the launcher's preemption forward) drains the whole
        fleet — same contract and plumbing as the single supervisor's
        handler."""
        handler, prev = install_drain_handler(self, signum)
        if handler is not None:
            self._prev_sigterm = prev
        return handler

    def uninstall_signal_handler(self, signum: int = signal.SIGTERM):
        uninstall_drain_handler(self._prev_sigterm, signum)
        self._prev_sigterm = None

    def drain(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        """Fleet-wide graceful drain: admissions stop everywhere,
        in-flight work finishes within the deadline, the remainder is
        cancelled. Returns the merged report — ``leaked_blocks`` sums
        every replica's pool and must read 0."""
        t0 = time.time()
        with self._lock:
            self.draining = True
            done_before = self.completed
            self.request_drain()
            deadline_s = (deadline_s if deadline_s is not None else
                          float(flag("FLAGS_serving_drain_deadline_s")))
        deadline = t0 + deadline_s
        while time.time() < deadline and self.pending:
            self.step()
        cancelled = leaked = 0
        with self._lock:
            for rep in self._replicas.values():
                rep_report = rep.sup.drain(0)
                cancelled += rep_report["cancelled"]
                leaked += rep_report["leaked_blocks"]
            self._sweep(time.time())
            report = {"completed": self.completed - done_before,
                      "cancelled": cancelled,
                      "leaked_blocks": int(leaked),
                      "duration_s": round(time.time() - t0, 3)}
        return report

    def close(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        report = self.drain(deadline_s)
        with self._lock:
            self.closed = True
        return report

    # ---- telemetry -----------------------------------------------------------

    def audit(self) -> Dict[str, Any]:
        """Run the :class:`~.audit.InvariantAuditor`'s structural checks
        against the whole fleet (production spelling: collects, never
        raises). The auditor instance persists across calls so the
        monotonic-counter baselines accumulate; ``health_snapshot()``
        folds the verdict in behind ``FLAGS_serving_audit``."""
        from .audit import InvariantAuditor
        with self._lock:
            if self._auditor is None:
                # bounded history: a production auditor scraped at 1 Hz
                # forever must not grow its trail/violation lists without
                # bound (replay auditors stay unbounded — the
                # determinism contract compares the full trail)
                self._auditor = InvariantAuditor(history=256)
            return self._auditor.audit(self)

    def health_snapshot(self) -> Dict[str, Any]:
        """The fleet ops payload — keys pinned to
        :data:`ROUTER_HEALTH_FIELDS` (docs/OPS.md "Serving fleet"). Shaped
        so :class:`ServingServer`'s ``/healthz``/``/readyz``/``/metrics``
        serve a router exactly as they serve one supervisor."""
        with self._lock:
            now = time.time()
            reps = {str(rid): rep.snapshot()
                    for rid, rep in self._replicas.items()}
            routable = [rid for rid, r in reps.items() if r["accepting"]]
            agg = self._aggregate()
            wd = _watchdog.current()
            roll = self._roll
            snap = {
                "ok": bool(reps) and any(not r["broken"]
                                         for r in reps.values())
                and (wd is None or not wd.fired.is_set()),
                "accepting": bool(routable) and not self._drain_requested
                and not self.draining and not self.closed,
                "queued": agg["queued"],
                "queue_limit": agg["queue_limit"],
                "live_slots": agg["live_slots"],
                "max_slots": agg["max_slots"],
                "retry_after_s": agg["retry_after_s"],
                "counters": {
                    "routed": self.routed,
                    "sticky_hits": self.sticky_hits,
                    "failovers": self.failovers,
                    "failover_tokens": self.failover_tokens,
                    "hedges": self.hedges,
                    "hedge_wins": self.hedge_wins,
                    "hedges_cancelled": self.hedges_cancelled,
                    "probe_failures": self.probe_failures,
                    "breaker_opens": self._opens_retired
                    + sum(r["breaker"]["opens"] for r in reps.values()),
                    "replica_restarts": self.replica_restarts,
                    "rolls_completed": self.rolls_completed,
                    "migrations": self.migrations,
                    "migration_tokens": self.migration_tokens,
                    "migration_fallbacks": self.migration_fallbacks,
                    "directory_hits": self.directory_hits,
                    "cache_pulls": self.cache_pulls,
                    "pulled_blocks": self.pulled_blocks,
                    "pull_fallbacks": self.pull_fallbacks,
                    "prefill_routed": self.prefill_routed,
                    "prefill_handoffs": self.prefill_handoffs,
                    "handoff_fallbacks": self.handoff_fallbacks,
                    "adapter_affinity_hits": self.adapter_affinity_hits,
                    "adapter_loads": self.adapter_loads,
                    "completed": self.completed,
                    "failed": self.failed,
                },
                "directory": ({"enabled": True,
                               **self._directory.snapshot()}
                              if self._directory is not None
                              else {"enabled": False}),
                "replicas": reps,
                "fleet": {
                    "size": len(reps),
                    "routable": len(routable),
                    "open_breakers": sum(
                        r["breaker"]["state"] != "closed"
                        for r in reps.values()),
                    "draining": sum(r["draining"] for r in reps.values()),
                    "retiring": sum(r["retiring"] for r in reps.values()),
                    "prefill": sum(r["role"] == "prefill"
                                   for r in reps.values()),
                },
                "roll": {
                    "active": roll is not None,
                    "target": roll["target"] if roll else None,
                    "pending": list(roll["pending"]) if roll else [],
                    "restarted": roll["restarted"] if roll else 0,
                },
                # PEEK the shed delta (autoscale_signal() owns advancing)
                "autoscale": autoscale_signal(
                    agg, shed_delta=max(
                        0, agg["counters"]["shed"] - self._last_shed)),
                "watchdog": {
                    "installed": wd is not None,
                    "fired": bool(wd.fired.is_set())
                    if wd is not None else False,
                    "timeout_s": wd.timeout if wd is not None else None,
                },
                # the production audit hook: FLAGS_serving_audit runs the
                # InvariantAuditor fleet-wide inside this snapshot (the
                # checks walk every block map — paid only when asked to)
                "audit": ({"enabled": True, **self.audit()}
                          if flag("FLAGS_serving_audit")
                          else {"enabled": False}),
                "supervisor": {
                    "draining": bool(self._drain_requested or self.draining),
                    "broken": bool(reps) and all(r["broken"]
                                                 for r in reps.values()),
                    "restarts": self._restarts_retired
                    + sum(r["restarts"] for r in reps.values()),
                    "restart_budget": sum(
                        rep.sup.max_restarts
                        for rep in self._replicas.values()),
                },
            }
            return snap

    def block_partitions(self) -> Dict[int, Dict[str, int]]:
        """Every replica's free/evictable/in-use/usable pool partition —
        the invariant (free + evictable + in_use == usable, per replica)
        the failover fuzz asserts every step."""
        with self._lock:
            return {rid: rep.sup.block_partition()
                    for rid, rep in self._replicas.items()}
