"""Continuous-batching scheduler — iteration-level request lifecycle.

Orca-style scheduling recast as pure host logic: a FIFO admission queue
feeding a fixed table of ``max_slots`` decode slots. Every engine step (1)
RETIRES slots whose request finished (EOS sampled or token budget spent),
returning their KV blocks to the pool, (2) ADMITS queued requests into free
slots while the block pool covers their PROMPT (on-demand allocation —
decode extends block by block as the sequence grows), and (3) hands the
engine the live slots for prefill-chunk and decode dispatches. When the
pool runs dry mid-decode the engine PREEMPTS the newest-admitted running
sequence (:meth:`Scheduler.preempt`): its blocks return to the pool, its
generated-so-far tokens are kept, and it re-queues at the FRONT for
recompute-on-readmission. The OLDEST running sequence is never preempted,
so at least one request always progresses — no livelock. The scheduler
never touches the device — the engine owns dispatch; this module owns WHO
is running WHERE and the per-request records (tokens, timestamps, prefix
hits, preemptions) the bench's stats come from.

Admission ORDER is a pluggable :class:`~.policies.AdmissionPolicy`
(FIFO default — strict submission order; priority / weighted fair share /
earliest-deadline-first ship alongside), and with reservation gone a large
queue head no longer charges its worst case up front — it admits on its
prompt footprint alone, and chunked prefill (engine-side) keeps a long
prompt from freezing in-flight decode streams.

Lifecycle (ISSUE 6): every request ends in exactly ONE terminal state —

    queued -> running -> FINISHED   (EOS / budget spent / oom-truncated)
                      -> CANCELLED  (engine.cancel / abandoned stream)
                      -> TIMED_OUT  (deadline passed after it started)
           ->          SHED         (deadline passed while queued, or the
                                     bounded queue refused the submit)

Terminal transitions release every block the request held (mid-flight via
the same free path preemption uses — free and do NOT requeue), so a stuck
or vanished consumer can never pin pool blocks, and the terminal record
(tokens so far, timestamps, counters) lands in ``finished`` like a normal
retirement. Per-tenant counters (queue depth, TTFT samples, shed/cancel/
timeout counts, service tokens) feed the engine's ``health_snapshot()``
and the fair-share policy.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ...flags import flag
from .policies import AdmissionPolicy, FIFOPolicy

__all__ = ["Request", "Scheduler", "ServingQueueFull",
           "completes_by_tokens",
           "QUEUED", "RUNNING", "FINISHED", "CANCELLED", "TIMED_OUT",
           "SHED", "TERMINAL_STATES"]


def completes_by_tokens(tokens, max_new_tokens: int,
                        eos_token_id: Optional[int]) -> bool:
    """Whether an already-delivered token list alone completes a request
    (budget spent, or EOS delivered last) — the ONE completion test the
    supervisor's and the router's recovery records share, so their views
    of "record it, don't re-run it" can never diverge."""
    if len(tokens) >= max_new_tokens:
        return True
    return (eos_token_id is not None and bool(tokens)
            and tokens[-1] == eos_token_id)

# request lifecycle states (Request.state)
QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
CANCELLED = "cancelled"
TIMED_OUT = "timed_out"
SHED = "shed"
TERMINAL_STATES = frozenset({FINISHED, CANCELLED, TIMED_OUT, SHED})

DEFAULT_TENANT = "default"


class ServingQueueFull(RuntimeError):
    """submit() beyond the admission queue's depth bound — the engine is
    LOAD SHEDDING instead of queueing unboundedly. Structured context for
    the caller's backoff logic (a 429/Retry-After response, a client-side
    retry budget):

    * ``queue_depth`` — requests queued when the submit was refused
    * ``live_slots`` — decode slots currently occupied
    * ``retry_after_s`` — suggested backoff: the scheduler's estimate of
      one retirement interval; before two retirements have been observed
      (cold start — nothing to estimate from) it is the conservative
      ``FLAGS_serving_retry_after_s`` default, never None/0
    """

    def __init__(self, message: str, queue_depth: Optional[int] = None,
                 live_slots: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.live_slots = live_slots
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class Request:
    """One generation request and its serving-side record."""

    rid: int
    prompt: np.ndarray                 # [S] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    # sampling knobs (ISSUE 11), RESOLVED through GenerationConfig at
    # submit: temperature 0 = greedy argmax (bit-identical to the v1
    # engine); top_k/top_p None = disabled; seed derives the per-request
    # PRNG base key — the token at sample index t is drawn with
    # fold_in(seed_key(seed), t), a pure function of (request, seed, t),
    # so sampled streams reproduce exactly across preemption-recompute,
    # supervisor crash-resubmit, cross-replica failover AND speculative
    # verify
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    # multi-tenancy + lifecycle (ISSUE 6): the tenant key scopes fair-share
    # accounting and cache quotas; priority orders the priority policy;
    # deadline is ABSOLUTE (time.time()) — engine.submit derives it from
    # timeout_s/deadline_s; state walks queued -> running -> one terminal
    tenant: str = DEFAULT_TENANT
    priority: int = 0
    deadline: Optional[float] = None
    state: str = QUEUED
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    eos_seen: bool = False
    blocks: Optional[List[int]] = None
    slot: Optional[int] = None
    # prefill progress: KV entries mapped-or-written so far (cache hits
    # count — their KV already exists). prefilling == num_computed short of
    # the full prefill set; the slot joins decode when they meet.
    num_computed: int = 0
    prefill_ids: Optional[np.ndarray] = None   # tokens prefill must cover
    admit_seq: int = -1                # admission order (newest = preempt
    #                                    victim; re-admission re-stamps)
    # incremental prefix-registration cursor: (full blocks registered,
    # chained key of the last one) — PagedKVCache.register_prefix state
    reg_state: Tuple[int, Optional[int]] = (0, None)
    # observability counters (engine stats() aggregates these)
    prefix_hit_tokens: int = 0
    preemptions: int = 0
    recomputed_tokens: int = 0
    spec_drafted: int = 0              # draft tokens verified for this
    spec_accepted: int = 0             # ... and how many were emitted
    # incremental n-gram presence index for the prompt-lookup drafter
    # (engine-owned; see ServingEngine._draft_tokens): {"end": positions
    # indexed so far, "seen": n-gram tuples ending before the context
    # end}. Survives preemption (the context it indexes — prompt +
    # kept tokens — never shrinks); a crash resubmission starts a fresh
    # Request and rebuilds it lazily.
    spec_index: Optional[Dict] = None
    computed_hwm: int = 0              # most KV entries ever written; caps
    #                                    the recompute charge on readmission
    #                                    (a mid-prefill preemption only
    #                                    repeats what it had finished)
    oom_truncated: bool = False        # pool exhausted with nothing left to
    #                                    preempt: retired early, output kept
    # durable serving (ISSUE 18): the journal record this request owns
    # (-1 = unjournaled). Ownership moves with the request across
    # migration / handoff / hedge resolution — the vacated copy is
    # DISOWNED before its cancel so the record stays live.
    jid: int = -1
    # multi-adapter LoRA (ISSUE 19): the adapter this request decodes
    # under (None = base traffic) and the device pool slot the engine's
    # admission gate pinned for it (0 = the zeroed base adapter). The
    # pin — and with it the slot — survives preemption: a readmission
    # must find the SAME weights resident, so the adapter releases only
    # at a terminal state.
    adapter_id: Optional[str] = None
    adapter_slot: int = 0
    # embeddings endpoint (ISSUE 19): kind "embed" requests are
    # prefill-only — they retire at prefill completion with the pooled
    # hidden states in ``embedding`` and never occupy a decode slot or
    # KV blocks (see Scheduler.admit_embeds)
    kind: str = "generate"
    embedding: Optional[np.ndarray] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def kv_tokens(self) -> int:
        """Worst-case KV entries: the prompt plus every generated token's
        KV except the last sampled token (its KV is never written)."""
        return self.prompt_len + self.max_new_tokens - 1

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def finished(self) -> bool:
        if self.kind == "embed":
            return self.embedding is not None
        return self.eos_seen or self.remaining <= 0 or self.oom_truncated

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    @property
    def prefilling(self) -> bool:
        return self.prefill_ids is not None and \
            self.num_computed < len(self.prefill_ids)

    def build_prefill_ids(self) -> np.ndarray:
        """The token ids prefill must compute KV for: the prompt, plus —
        after a preemption — every generated token except the last (whose
        KV the first decode step writes). Greedy determinism makes the
        recomputed KV bit-identical to what was freed."""
        if self.tokens:
            return np.concatenate(
                [self.prompt, np.asarray(self.tokens[:-1], np.int32)])
        return self.prompt

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def tok_latency_s(self) -> Optional[float]:
        """Mean decode latency per token after the first — the request's
        TPOT sample. None for 1-token requests and for crash-recovered
        resubmissions (their first token predates this engine, so no
        ``first_token_t`` exists to measure from)."""
        if self.finish_t is None or self.first_token_t is None \
                or len(self.tokens) < 2:
            return None
        return (self.finish_t - self.first_token_t) / (len(self.tokens) - 1)

    def output(self) -> np.ndarray:
        return np.asarray(self.tokens, np.int32)


class Scheduler:
    """Policy-ordered admission queue + slot table over a
    :class:`PagedKVCache`.

    ``preempt=True`` (the default) is the on-demand mode: admission maps
    prefix-cache hits and allocates only the prompt's remaining blocks;
    ``preempt=False`` restores the legacy worst-case reservation (no
    preemption machinery needed, conservative admission). ``policy`` is
    an :class:`~.policies.AdmissionPolicy` (default FIFO) choosing which
    queued request admits next.
    """

    # hostile traffic can mint a new tenant string per request; past this
    # many distinct tenants new ones aggregate under one overflow key so
    # the stats dict cannot grow without bound
    MAX_TENANTS = 256
    _OVERFLOW_TENANT = "_overflow"
    # TTFT samples retained per tenant for the health snapshot's p50/p99
    TTFT_SAMPLES = 128

    def __init__(self, cache, max_slots: int, queue_depth: int,
                 preempt: bool = True,
                 policy: Optional[AdmissionPolicy] = None):
        self.cache = cache
        self.max_slots = int(max_slots)
        self.queue_depth = int(queue_depth)
        self.preempt_enabled = bool(preempt)
        self.policy = policy if policy is not None else FIFOPolicy()
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * max_slots
        # finished-record retention is BOUNDED (a long-lived engine must
        # not leak every prompt it ever served): insertion-ordered dict,
        # oldest evicted past queue_depth + 2*max_slots — the most
        # requests that can be in flight at once (a supervisor crash
        # resubmission bypasses the queue bound by up to max_slots, plus
        # the slots themselves), so one mass termination (drain
        # cancel_all) can never evict a record before the supervisor's
        # sweep collects it, and one full run()/drain cycle can always
        # collect its results afterwards. Terminal records
        # (cancelled/timed-out/shed) land here too.
        self.finished: Dict[int, Request] = {}
        self.keep_finished = self.queue_depth + 2 * self.max_slots
        self._next_rid = 0
        self._admit_seq = 0
        self.admitted = 0
        self.retired = 0
        self.preemptions = 0
        self.prefix_hit_tokens = 0
        self.recomputed_tokens = 0
        self.oom_truncated = 0
        # speculative-decoding totals (ISSUE 11): drafts verified vs
        # drafts emitted — the live acceptance-rate signal
        self.spec_drafted = 0
        self.spec_accepted = 0
        # lifecycle counters (terminal states other than FINISHED)
        self.cancelled = 0
        self.timed_out = 0
        self.shed = 0
        # live requests carrying a deadline — the engine skips the
        # per-step expiry sweep entirely while this is 0
        self.deadline_requests = 0
        # recent retirement timestamps -> the retry-after estimate; the
        # conservative default covers the cold-start window before two
        # retirements exist to measure an interval from
        self._finish_times: Deque[float] = deque(maxlen=16)
        self.default_retry_after_s = float(
            flag("FLAGS_serving_retry_after_s", 1.0))
        # absolute time the active drain completes (stamped by the
        # supervisor's request_drain/drain): while set and in the future,
        # retry_after_s() reports the drain-deadline REMAINDER — a client
        # shed by a leaving replica must not be told to retry into it on
        # the retirement-interval estimate (ISSUE 16 satellite)
        self.drain_deadline: Optional[float] = None
        self.tenants: Dict[str, Dict] = {}

    # ---- per-tenant accounting ---------------------------------------------

    def tenant(self, name: str) -> Dict:
        """The (lazily created) stats record for one tenant key."""
        d = self.tenants.get(name)
        if d is None:
            if len(self.tenants) >= self.MAX_TENANTS and \
                    name != self._OVERFLOW_TENANT:
                return self.tenant(self._OVERFLOW_TENANT)
            d = self.tenants[name] = {
                "submitted": 0, "admitted": 0, "retired": 0,
                "cancelled": 0, "timed_out": 0, "shed": 0,
                "service_tokens": 0,
                "ttfts": deque(maxlen=self.TTFT_SAMPLES),
                "tpots": deque(maxlen=self.TTFT_SAMPLES),
            }
        return d

    def by_tenant(self) -> Dict[str, Dict[str, int]]:
        """Queued/live request counts per TENANT ROW — tenants past
        ``MAX_TENANTS`` fold into the overflow row exactly as
        :meth:`tenant` folded their counters at submit, so the rows
        always close against the counter dict. The ONE folding used by
        the engine's ``health_snapshot()`` per-tenant breakdown and the
        InvariantAuditor's accounting-closure check."""
        def tkey(name: str) -> str:
            return name if name in self.tenants else self._OVERFLOW_TENANT

        out = {name: {"queued": 0, "live": 0} for name in self.tenants}
        for r in self.queue:
            out[tkey(r.tenant)]["queued"] += 1
        for r in self.slots:
            if r is not None:
                out[tkey(r.tenant)]["live"] += 1
        return out

    @property
    def prefill_queue_depth(self) -> int:
        """Requests still ahead of their FIRST token on this replica:
        everything queued plus live slots mid-prefill. The backlog a
        prefill-pool replica's retry hint must account for — and the
        saturation signal the router's prefill-pool sizing reads."""
        return len(self.queue) + \
            sum(1 for r in self.live if r.prefilling)

    def retry_after_s(self) -> float:
        """Suggested backoff when shedding: the mean interval between the
        most recent retirements (one retirement frees one slot, which is
        what drains one queued request), SCALED by the prefill backlog —
        a shed request re-arriving after one mean retirement interval
        meets the same full queue if ``prefill_queue_depth`` requests
        are still ahead of it, so the hint multiplies the interval by
        the backlog (floor 1: an idle replica keeps the plain estimate).
        Before two retirements have been observed there is no interval
        to estimate, so the conservative ``FLAGS_serving_retry_after_s``
        default is returned instead of a degenerate None/0 a client
        would turn into a hot retry loop.

        During an ACTIVE drain the retirement-interval estimate is the
        wrong signal entirely — this replica is leaving, and a client
        retrying into it on a sub-second interval estimate just gets
        shed again. The hint becomes the drain deadline REMAINDER: after
        that long, this replica is gone and the retry belongs to
        whatever replaced it."""
        if self.drain_deadline is not None:
            remaining = self.drain_deadline - time.time()
            if remaining > 0:
                return round(remaining, 3)
        if len(self._finish_times) < 2:
            return self.default_retry_after_s
        span = self._finish_times[-1] - self._finish_times[0]
        if span <= 0:
            return 0.001
        est = span / (len(self._finish_times) - 1)
        return round(est * max(1, self.prefill_queue_depth), 3)

    # ---- lifecycle --------------------------------------------------------

    def submit(self, req: Request, enforce_bound: bool = True) -> int:
        """Queue one request. ``enforce_bound=False`` bypasses the
        queue-depth shed — the supervisor's crash-recovery resubmission
        path, where every request was ALREADY accepted once and the
        re-queued set (old queue + old slots) can legitimately exceed the
        admission bound by up to ``max_slots``."""
        if enforce_bound and len(self.queue) >= self.queue_depth:
            # SHED, don't queue: a bounded queue with a retry-after hint
            # keeps tail latency bounded under overload — an unbounded one
            # converts overload into unbounded TTFT for everyone
            self.shed += 1
            self.tenant(req.tenant)["shed"] += 1
            ra = self.retry_after_s()
            hint = f"; retry in ~{ra}s" if ra is not None else ""
            raise ServingQueueFull(
                f"admission queue full ({self.queue_depth}): request shed"
                f"{hint}; drain with step()/stream() or raise "
                f"FLAGS_serving_queue_depth",
                queue_depth=len(self.queue), live_slots=len(self.live),
                retry_after_s=ra)
        # fail fast on requests the pool can NEVER hold (vs transiently
        # full); the bound is KV entries, not blocks — block granularity
        # would admit up to block_size-1 entries past max_model_len.
        # Embedding requests (ISSUE 19) bypass both: they run through the
        # encoder without KV blocks, so pool geometry cannot reject them.
        if req.kind != "embed":
            if req.kv_tokens > self.cache.max_model_len:
                raise ValueError(
                    f"request needs {req.kv_tokens} KV entries "
                    f"(prompt {req.prompt_len} + {req.max_new_tokens} new) "
                    f"> max_model_len {self.cache.max_model_len}")
            usable = self.cache.manager.num_blocks - 1  # block 0 is null
            if self.preempt_enabled:
                # on-demand: only the PROMPT must fit the pool (a max_new
                # worst case is a budget, not a charge — EOS usually lands
                # first, and a genuinely over-budget sole survivor is
                # truncated, not hung)
                n = self.cache.manager.blocks_for(req.prompt_len)
                what = f"prompt ({req.prompt_len} tokens)"
            else:
                # reservation mode admits only full worst-case footprints
                n = self.cache.manager.blocks_for(req.kv_tokens)
                what = f"worst case ({req.kv_tokens} KV entries)"
            if n > usable:
                raise ValueError(
                    f"request {what} needs {n} KV blocks but the pool only "
                    f"has {usable} usable blocks (num_blocks="
                    f"{self.cache.manager.num_blocks} incl. the null "
                    f"block); admitting it would wait forever")
        req.rid = self._next_rid
        self._next_rid += 1
        req.submit_t = time.time()
        req.state = QUEUED
        if req.deadline is not None:
            self.deadline_requests += 1
        self.tenant(req.tenant)["submitted"] += 1
        self.queue.append(req)
        return req.rid

    def next_admission(self, gate=None) -> Optional[Request]:
        """Pop the policy's pick into a free slot if its blocks fit; None
        when nothing can be admitted this iteration. On-demand mode maps
        prefix-cache hits and allocates only the remaining prompt blocks;
        reservation mode allocates the full worst case. Admission never
        preempts running work — it waits for retirement to free blocks,
        and is head-of-line PER THE POLICY'S ORDER: when the pick's
        blocks don't fit, admission waits rather than skipping to a
        smaller request (skipping would starve large requests).

        ``gate`` (ISSUE 19) is the engine's adapter-pool admission hook:
        called with the pick BEFORE any blocks are allocated, returning
        False when the pick cannot be seated right now (its adapter has
        no free pool slot — every slot pinned by running requests). A
        gated-out pick is SKIPPED for this iteration only — the policy
        re-selects among the remaining candidates, so one starved
        adapter never head-of-line blocks base traffic or other
        adapters — and stays queued for the next step, when a
        retirement may have unpinned a slot."""
        candidates = [r for r in self.queue if r.kind != "embed"]
        while candidates:
            if not [m for m, r in enumerate(self.slots) if r is None]:
                return None
            # a preempted request re-queued at the FRONT outranks any
            # policy pick: its generated tokens are already paid for, and
            # the no-livelock argument assumes it readmits at the next
            # retirement
            if candidates[0] is self.queue[0] and self.queue[0].preemptions:
                req = candidates[0]
            else:
                req = self.policy.select(candidates, self, time.time())
            if gate is None or gate(req):
                break
            candidates.remove(req)
        else:
            return None
        free = [m for m, r in enumerate(self.slots) if r is None]
        ids = req.build_prefill_ids()
        res = self.cache.admit(
            ids, reserve_kv=None if self.preempt_enabled else req.kv_tokens,
            namespace=req.adapter_id)
        if res is None:
            return None                       # the pick waits for blocks
        blocks, hit, reg_state = res
        self.queue.remove(req)
        slot = free[0]
        req.blocks, req.slot = blocks, slot
        req.prefill_ids = ids
        req.num_computed = hit
        req.reg_state = reg_state
        req.prefix_hit_tokens += hit
        self.prefix_hit_tokens += hit
        if req.preemptions:
            # KV this readmission re-runs prefill over: cache hits exempt,
            # and never more than the request ever actually computed
            rec = max(0, min(req.computed_hwm, len(ids)) - hit)
            req.recomputed_tokens += rec
            self.recomputed_tokens += rec
        req.admit_seq = self._admit_seq
        self._admit_seq += 1
        req.state = RUNNING
        self.cache.assign(slot, blocks)
        self.slots[slot] = req
        self.admitted += 1
        t = self.tenant(req.tenant)
        t["admitted"] += 1
        t["service_tokens"] += req.prompt_len     # prefill work charged now
        return req

    def adopt_running(self, req: Request, slot: int,
                      blocks: List[int]) -> int:
        """Seat a MIGRATED request (ISSUE 16) directly into a slot,
        bypassing the queue: its KV chain arrived with it, so there is
        no prefill to schedule and no admission to wait for. The engine
        has already allocated ``blocks`` and written the chain; this
        stamps the full submit+admit bookkeeping (rid, timestamps,
        counters, tenant accounting) in one step so every closure
        invariant the auditor checks (submitted >= admitted >= ...,
        tenant rows, deadline_requests) holds exactly as if the request
        had been submitted and admitted here."""
        if self.slots[slot] is not None:
            raise RuntimeError(f"adopt into occupied slot {slot}")
        req.rid = self._next_rid
        self._next_rid += 1
        req.submit_t = time.time()
        if req.deadline is not None:
            self.deadline_requests += 1
        t = self.tenant(req.tenant)
        t["submitted"] += 1
        req.blocks, req.slot = blocks, slot
        req.admit_seq = self._admit_seq
        self._admit_seq += 1
        req.state = RUNNING
        self.slots[slot] = req
        self.admitted += 1
        t["admitted"] += 1
        t["service_tokens"] += req.prompt_len
        return req.rid

    def admit_embeds(self) -> List[Request]:
        """Pop EVERY queued embedding request (``kind == "embed"``) for
        the engine's batched encoder dispatch (ISSUE 19). Embeds need no
        decode slot and no KV blocks, so admission is unconditional and
        slot-free; the engine completes the whole batch — encoder
        forward, pooled output, :meth:`finish` — inside the same locked
        step, so no observer ever sees a RUNNING request without a slot.
        Stamps the full admit bookkeeping so the auditor's accounting
        closure (admitted >= retired, tenant rows) holds exactly as for
        generate traffic."""
        out = [r for r in self.queue if r.kind == "embed"]
        for req in out:
            self.queue.remove(req)
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            req.state = RUNNING
            self.admitted += 1
            t = self.tenant(req.tenant)
            t["admitted"] += 1
            t["service_tokens"] += req.prompt_len
        return out

    def preempt(self, req: Request) -> None:
        """Free a RUNNING request's blocks and re-queue it at the FRONT for
        recompute-on-readmission (tokens kept — greedy recompute is
        bit-identical). The engine calls this only when the pool is dry,
        picking its newest-admitted victim via :meth:`preempt_victim`."""
        done = (req.num_computed if req.prefilling
                else req.prompt_len + max(len(req.tokens) - 1, 0))
        req.computed_hwm = max(req.computed_hwm, done)
        self.cache.release(req.slot, req.blocks)
        self.slots[req.slot] = None
        req.blocks, req.slot = None, None
        req.num_computed = 0
        req.prefill_ids = None
        req.reg_state = (0, None)          # readmission re-seeds from hits
        req.preemptions += 1
        self.preemptions += 1
        req.state = QUEUED
        self.queue.appendleft(req)

    def preempt_victim(self) -> Optional[Request]:
        """The newest-admitted live request — UNLESS it is the only one
        (the oldest is never preempted; its monotonic progress is the
        livelock-freedom proof)."""
        live = [r for r in self.slots if r is not None]
        if len(live) < 2:
            return None
        return max(live, key=lambda r: r.admit_seq)

    def finish(self, req: Request) -> None:
        """Mark finished + free its KV back to the pool."""
        self._release(req)
        req.state = FINISHED
        self._record(req)
        self.retired += 1
        self._finish_times.append(req.finish_t)
        t = self.tenant(req.tenant)
        t["retired"] += 1
        t["service_tokens"] += len(req.tokens)    # decode work charged here
        if req.ttft_s is not None:
            t["ttfts"].append(req.ttft_s)
        if req.tok_latency_s is not None:
            t["tpots"].append(req.tok_latency_s)

    def terminate(self, req: Request, state: str) -> None:
        """Force a queued or running request into a terminal state —
        CANCELLED (explicit cancel / abandoned stream), TIMED_OUT
        (deadline passed after it started), or SHED (deadline passed
        while still queued). Frees any blocks it holds via the same path
        preemption uses (free, do NOT requeue) and records it in
        ``finished`` so ``result()``/``request()`` still find the partial
        output. The caller (engine) is responsible for clearing its slot
        arrays when the request held a slot."""
        assert state in TERMINAL_STATES and state != FINISHED, state
        if req.slot is None:
            # queued (possibly preempted-and-requeued): no blocks held
            try:
                self.queue.remove(req)
            except ValueError:
                pass
        self._release(req)
        req.state = state
        self._record(req)
        counter = {CANCELLED: "cancelled", TIMED_OUT: "timed_out",
                   SHED: "shed"}[state]
        setattr(self, counter, getattr(self, counter) + 1)
        t = self.tenant(req.tenant)
        t[counter] += 1
        t["service_tokens"] += len(req.tokens)
        if req.tok_latency_s is not None:     # timed-out/cancelled partials
            t["tpots"].append(req.tok_latency_s)    # are real decode work

    def _release(self, req: Request) -> None:
        req.finish_t = time.time()
        if req.blocks is not None:
            # blocks and slot are only ever assigned together in
            # next_admission, so a request with blocks always holds a slot
            self.cache.release(req.slot, req.blocks)
            self.slots[req.slot] = None
            req.blocks = None
        req.slot = None
        if req.deadline is not None:
            self.deadline_requests -= 1

    def _record(self, req: Request) -> None:
        self.finished[req.rid] = req
        while len(self.finished) > self.keep_finished:
            del self.finished[next(iter(self.finished))]

    def find(self, rid: int) -> Optional[Request]:
        """The queued or running request with this id (None when unknown
        or already terminal)."""
        for r in self.queue:
            if r.rid == rid:
                return r
        for r in self.slots:
            if r is not None and r.rid == rid:
                return r
        return None

    def retire_finished(self) -> List[Request]:
        done = [r for r in self.slots if r is not None and r.finished]
        for r in done:
            self.finish(r)
        return done

    # ---- introspection ----------------------------------------------------

    @property
    def live(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def decoding(self) -> List[Request]:
        """Live requests past prefill (the decode dispatch's active set)."""
        return [r for r in self.slots if r is not None and not r.prefilling]

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.slots)

    @property
    def depth(self) -> int:
        """Outstanding work — queued plus live requests. The router's
        power-of-two-choices load signal: cheap enough to read per
        submit, and proportional to the time a new admission waits."""
        return len(self.queue) + sum(r is not None for r in self.slots)

    def result(self, rid: int) -> np.ndarray:
        return self.finished[rid].output()
