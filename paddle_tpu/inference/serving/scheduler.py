"""Continuous-batching scheduler — iteration-level request lifecycle.

Orca-style scheduling recast as pure host logic: a FIFO admission queue
feeding a fixed table of ``max_slots`` decode slots. Every engine step (1)
RETIRES slots whose request finished (EOS sampled or token budget spent),
returning their KV blocks to the pool, (2) ADMITS queued requests into free
slots while the block pool can reserve their worst-case footprint, and (3)
hands the engine the set of live slots for one fixed-shape decode dispatch.
The scheduler never touches the device — the engine owns dispatch; this
module owns WHO is running WHERE and the per-request records (tokens,
timestamps) the bench's TTFT/latency percentiles come from.

FIFO is strict: a queue head too large for the currently-free blocks blocks
later, smaller requests (head-of-line; no deadlock — running slots always
retire and their blocks return, and submit() rejects requests larger than
the whole pool up front).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional

import numpy as np

__all__ = ["Request", "Scheduler", "ServingQueueFull"]


class ServingQueueFull(RuntimeError):
    """submit() beyond the admission queue's depth bound."""


@dataclasses.dataclass
class Request:
    """One generation request and its serving-side record."""

    rid: int
    prompt: np.ndarray                 # [S] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    eos_seen: bool = False
    blocks: Optional[List[int]] = None
    slot: Optional[int] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def kv_tokens(self) -> int:
        """Worst-case KV entries: the prompt plus every generated token's
        KV except the last sampled token (its KV is never written)."""
        return self.prompt_len + self.max_new_tokens - 1

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def finished(self) -> bool:
        return self.eos_seen or self.remaining <= 0

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def tok_latency_s(self) -> Optional[float]:
        """Mean decode latency per token after the first (None for 1-token
        requests)."""
        if self.finish_t is None or len(self.tokens) < 2:
            return None
        return (self.finish_t - self.first_token_t) / (len(self.tokens) - 1)

    def output(self) -> np.ndarray:
        return np.asarray(self.tokens, np.int32)


class Scheduler:
    """FIFO admission queue + slot table over a :class:`PagedKVCache`."""

    def __init__(self, cache, max_slots: int, queue_depth: int):
        self.cache = cache
        self.max_slots = int(max_slots)
        self.queue_depth = int(queue_depth)
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * max_slots
        # finished-record retention is BOUNDED (a long-lived engine must
        # not leak every prompt it ever served): insertion-ordered dict,
        # oldest evicted past queue_depth + max_slots — enough that one
        # full run()/drain cycle (submit bounded by queue_depth) can
        # always collect its results afterwards
        self.finished: Dict[int, Request] = {}
        self.keep_finished = self.queue_depth + self.max_slots
        self._next_rid = 0
        self.admitted = 0
        self.retired = 0

    # ---- lifecycle --------------------------------------------------------

    def submit(self, req: Request) -> int:
        if len(self.queue) >= self.queue_depth:
            raise ServingQueueFull(
                f"admission queue full ({self.queue_depth}); drain with "
                f"step()/stream() or raise FLAGS_serving_queue_depth")
        # fail fast on requests the pool can NEVER hold (vs transiently
        # full); the bound is KV entries, not blocks — block granularity
        # would admit up to block_size-1 entries past max_model_len
        if req.kv_tokens > self.cache.max_model_len:
            raise ValueError(
                f"request needs {req.kv_tokens} KV entries "
                f"(prompt {req.prompt_len} + {req.max_new_tokens} new) > "
                f"max_model_len {self.cache.max_model_len}")
        n = self.cache.manager.blocks_for(req.kv_tokens)
        usable = self.cache.manager.num_blocks - 1      # block 0 is null
        if n > usable:
            raise ValueError(
                f"request needs {n} KV blocks but the pool only has "
                f"{usable} usable blocks (num_blocks="
                f"{self.cache.manager.num_blocks} incl. the null block); "
                f"admitting it would wait forever")
        req.rid = self._next_rid
        self._next_rid += 1
        req.submit_t = time.time()
        self.queue.append(req)
        return req.rid

    def next_admission(self) -> Optional[Request]:
        """Pop the queue head into a free slot if its blocks fit; None when
        nothing can be admitted this iteration."""
        if not self.queue:
            return None
        free = [m for m, r in enumerate(self.slots) if r is None]
        if not free:
            return None
        req = self.queue[0]
        blocks = self.cache.reserve(req.kv_tokens)
        if blocks is None:
            return None                       # head-of-line waits for blocks
        self.queue.popleft()
        slot = free[0]
        req.blocks, req.slot = blocks, slot
        self.cache.assign(slot, blocks)
        self.slots[slot] = req
        self.admitted += 1
        return req

    def finish(self, req: Request) -> None:
        """Mark finished + free its KV back to the pool."""
        req.finish_t = time.time()
        if req.blocks is not None:
            # blocks and slot are only ever assigned together in
            # next_admission, so a request with blocks always holds a slot
            self.cache.release(req.slot, req.blocks)
            self.slots[req.slot] = None
            req.blocks = None
        req.slot = None
        self.finished[req.rid] = req
        while len(self.finished) > self.keep_finished:
            del self.finished[next(iter(self.finished))]
        self.retired += 1

    def retire_finished(self) -> List[Request]:
        done = [r for r in self.slots if r is not None and r.finished]
        for r in done:
            self.finish(r)
        return done

    # ---- introspection ----------------------------------------------------

    @property
    def live(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.slots)

    def result(self, rid: int) -> np.ndarray:
        return self.finished[rid].output()
