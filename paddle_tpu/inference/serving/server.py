"""Asyncio serving front line: one event loop, many clients, one
supervised engine thread (docs/OPS.md "Serving front line").

Nothing stood between a network client and the engine: no streaming
transport, no supervision when the step loop dies, no drain on SIGTERM.
:class:`ServingServer` is that missing layer:

* **Thread-safe submission bridge.** Engine calls stay on ONE dedicated
  engine thread (the pump): clients post submit/cancel commands onto a
  thread-safe queue the pump consumes between iterations, and receive
  token/finish events on per-client ``asyncio.Queue``\\ s fed via
  ``loop.call_soon_threadsafe`` — the event loop multiplexes any number
  of clients without ever touching the device.

* **SSE-style token events.** A stream yields dict events — ``start``,
  ``token`` (one per generated token), ``finish`` (the serving record:
  state/TTFT/TPOT/prefix-hit/preemption counters), ``disconnect`` — and
  the TCP transport encodes them as ``text/event-stream`` frames. Tier-1
  tests ride the in-process transport (:meth:`ServingServer.handle` /
  :meth:`agenerate`): same handler, no sockets, no flakes.

* **Per-client backpressure.** Each client buffer is bounded
  (``FLAGS_serving_client_queue``); a consumer that falls that far behind
  is a SLOW CONSUMER — it is disconnected and its request cancelled
  through ``engine.cancel()``, freeing KV immediately (the same contract
  ``stream()`` gives ``GeneratorExit``). Closing/abandoning a stream
  cancels the same way, so a vanished SSE client can never pin the pool.

* **Supervision + drain + ops endpoints.** The pump drives
  :class:`~.supervisor.EngineSupervisor` — crash barrier, restart budget,
  resubmission — and reacts to its drain flag (SIGTERM via
  :meth:`install_signal_handlers`, or :meth:`close`): admissions get the
  structured 503 + ``retry_after_s``, in-flight work finishes within the
  deadline, the remainder is cancelled. ``/healthz`` (liveness),
  ``/readyz`` (accepting ∧ restart budget intact) and ``/metrics`` (the
  full health snapshot + TPOT per tenant + the autoscale signal) serve
  the supervisor's payload.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import json
import queue as _tqueue
import signal as _signal
import threading
import time
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from ...flags import flag
from .scheduler import ServingQueueFull
from .supervisor import EngineSupervisor, ServingUnavailable

__all__ = ["ServingServer", "ClientStream", "sse_encode"]


def sse_encode(event: Dict[str, Any]) -> bytes:
    """One Server-Sent-Events frame: ``event:`` carries the type,
    ``data:`` the JSON payload."""
    return (f"event: {event.get('type', 'message')}\n"
            f"data: {json.dumps(event)}\n\n").encode()


class ClientStream:
    """One client's event pipe. The pump thread feeds ``q`` through the
    loop; the consumer iterates :meth:`events`. ``dropped`` flips when
    the bounded buffer overflows (slow consumer) — the server cancels
    the request the moment that happens, and the consumer sees a
    terminal ``disconnect`` event after draining what was delivered."""

    def __init__(self, maxsize: int):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=max(1, maxsize))
        self.srid: Optional[int] = None
        self.dropped = False
        self.closed = False
        self.done = False

    async def events(self) -> AsyncIterator[Dict[str, Any]]:
        while True:
            if self.dropped and self.q.empty():
                yield {"type": "disconnect", "reason": "slow_consumer",
                       "rid": self.srid}
                return
            try:
                ev = await asyncio.wait_for(self.q.get(), timeout=0.05)
            except asyncio.TimeoutError:
                if self.done and self.q.empty():
                    return
                continue
            if ev is None:                      # end-of-stream sentinel
                return
            yield ev


class ServingServer:
    """The asyncio front line over one :class:`EngineSupervisor`.

    Lifecycle::

        sup = EngineSupervisor(params, cfg, ServingConfig(...))
        srv = ServingServer(sup)
        async with srv.running():               # starts the engine thread
            async for ev in srv.agenerate(prompt, max_new_tokens=32):
                ...                             # in-process, port-free
        # srv.close() ran: drained, cancelled the rest, joined the pump

    ``await srv.start_tcp(host, port)`` inside ``running()`` additionally
    serves the same handler over HTTP/1.1 + SSE on a real socket.
    """

    def __init__(self, supervisor,
                 client_queue: Optional[int] = None,
                 poll_s: float = 0.02):
        # `supervisor` is an EngineSupervisor OR a ServingRouter — both
        # speak the same submit/cancel/step/pending/drain/health_snapshot
        # contract, so one server front-lines a single replica or a fleet
        self.sup = supervisor
        self.client_queue = int(client_queue if client_queue is not None
                                else flag("FLAGS_serving_client_queue"))
        self._poll_s = float(poll_s)
        self._cmds: _tqueue.Queue = _tqueue.Queue()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._open: Dict[int, ClientStream] = {}    # srid -> live stream
        self._tcp: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self.drain_report: Optional[Dict[str, Any]] = None
        self.pump_error: Optional[BaseException] = None

    @classmethod
    def cold_start(cls, journal_dir: str, params, model_config,
                   serving_config=None, gen_config=None,
                   replicas: Optional[int] = None, router_config=None,
                   programs=None, **server_kw) -> "ServingServer":
        """Build a server over a crash-recovered backend (ISSUE 18): a
        :meth:`~.router.ServingRouter.cold_start` fleet when
        ``replicas``/``router_config`` is given, else a single
        :meth:`EngineSupervisor.recover` replica. Every request the dead
        process had journaled and not finished resumes bit-exactly; its
        SIGTERM path (``install_signal_handlers`` → drain) flushes the
        journal and writes a final snapshot before exit, closing the
        durability loop for the next cold start."""
        if replicas is not None or router_config is not None:
            from .router import ServingRouter
            backend = ServingRouter.cold_start(
                journal_dir, params, model_config, serving_config,
                gen_config, router_config=router_config,
                replicas=replicas, programs=programs)
        else:
            backend = EngineSupervisor.recover(
                journal_dir, params, model_config, serving_config,
                gen_config, programs=programs)
        return cls(backend, **server_kw)

    # ---- lifecycle ---------------------------------------------------------

    async def start_pump(self) -> None:
        """Bind to the running loop and start the engine thread."""
        if self._thread is not None:
            return
        self._loop = asyncio.get_running_loop()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="serving-pump")
        self._thread.start()

    @contextlib.asynccontextmanager
    async def running(self, host: Optional[str] = None, port: int = 0):
        await self.start_pump()
        if host is not None:
            await self.start_tcp(host, port)
        try:
            yield self
        finally:
            await self.close()

    def install_signal_handlers(self) -> bool:
        """SIGTERM (the launcher's preemption forward) requests a
        graceful drain on the pump thread. Uses the loop's handler when
        possible; returns False when signals can't be installed here."""
        try:
            self._loop.add_signal_handler(_signal.SIGTERM,
                                          self.sup.request_drain)
            return True
        except (NotImplementedError, RuntimeError, ValueError):
            return self.sup.install_signal_handler() is not None

    async def close(self, deadline_s: Optional[float] = None
                    ) -> Optional[Dict[str, Any]]:
        """Graceful shutdown: stop the TCP listener, drain the supervisor
        (admissions 503, in-flight finished within the deadline, rest
        cancelled), then stop and join the pump thread. Returns the drain
        report."""
        if self._tcp is not None:
            self._tcp.close()
            with contextlib.suppress(Exception):
                await self._tcp.wait_closed()
            self._tcp = None
        if self._thread is None:
            return self.drain_report
        if self.drain_report is None:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            self._cmds.put(("drain", deadline_s, None, fut))
            self.drain_report = await asyncio.wrap_future(fut)
        self._stop.set()
        await asyncio.get_running_loop().run_in_executor(
            None, self._thread.join, 10.0)
        self._thread = None
        return self.drain_report

    # ---- the engine thread -------------------------------------------------

    def _pump(self) -> None:
        """The single engine thread: consume commands, drive the
        supervised step loop, route events. Every engine/scheduler call
        in the process happens here (or under the engine lock), which is
        what makes the asyncio side safe. One iteration failing must not
        kill the thread — a dead pump strands every client and hangs
        close() — so the body runs under its own barrier; the last error
        is kept for /healthz."""
        while not self._stop.is_set():
            try:
                self._pump_once()
            except Exception as e:                # noqa: BLE001 — barrier
                self.pump_error = e
                time.sleep(self._poll_s)

    def _pump_once(self) -> None:
        busy = self.sup.pending
        self._run_cmds(block=not busy)
        if self.sup.drain_requested and self.drain_report is None:
            self._drain_now(None)
            return
        # route finishes even when idle: a broken flip or an external
        # cancel must still deliver terminal events to open streams
        self._route_finishes()
        if not self.sup.pending:
            return
        emitted = self.sup.step(self._decode_chunk())
        for srid, toks in emitted.items():
            client = self._open.get(srid)
            if client is None:
                continue
            for t in toks:
                self._deliver(client, {"type": "token", "rid": srid,
                                       "token": int(t)})
        self._route_finishes()

    def _decode_chunk(self) -> int:
        """Streaming-granularity cap per pump iteration: the router
        exposes it directly (one shared ServingConfig), a bare
        supervisor through its engine."""
        chunk = getattr(self.sup, "decode_chunk", None)
        if chunk is not None:
            return int(chunk)
        return int(self.sup.engine.config.decode_chunk)

    def _run_cmds(self, block: bool) -> None:
        try:
            cmd = self._cmds.get(timeout=self._poll_s) if block \
                else self._cmds.get_nowait()
        except _tqueue.Empty:
            return
        while True:
            self._run_cmd(cmd)
            try:
                cmd = self._cmds.get_nowait()
            except _tqueue.Empty:
                return

    def _run_cmd(self, cmd) -> None:
        kind, payload, client, fut = cmd
        if kind == "submit":
            try:
                srid = self.sup.submit(**payload)
                if client is not None:
                    client.srid = srid
                    self._open[srid] = client
                if fut is not None:
                    fut.set_result(srid)
            except Exception as e:                # noqa: BLE001 — to caller
                if fut is not None:
                    fut.set_exception(e)
        elif kind == "cancel":
            ok = self.sup.cancel(payload)
            self._route_finishes()
            if fut is not None:
                fut.set_result(ok)
        elif kind == "drain":
            self._drain_now(payload)
            if fut is not None:
                fut.set_result(self.drain_report)

    def _drain_now(self, deadline_s) -> None:
        if self.drain_report is None:       # SIGTERM and close() can race
            self.drain_report = self.sup.drain(deadline_s)
        self._route_finishes()

    def _route_finishes(self) -> None:
        """Terminal transitions -> finish events + end-of-stream
        sentinels for the affected clients."""
        for srid in list(self._open):
            rec = self.sup._reqs.get(srid)
            if rec is None or not rec.terminal:
                continue
            # default: an abandoning consumer (agenerate's finally, loop
            # thread) can pop the same srid between the snapshot above
            # and here — losing that race must not kill the pump
            client = self._open.pop(srid, None)
            if client is None:
                continue
            fin = dict(rec.finish or {"state": rec.state,
                                      "tokens": len(rec.tokens)})
            fin.update({"type": "finish", "rid": srid})
            self._deliver(client, fin)
            self._deliver(client, None)

    def _deliver(self, client: ClientStream, ev) -> None:
        """Pump thread -> loop: enqueue one event on the client's bounded
        buffer. Overflow = slow consumer: mark dropped and cancel its
        request so abandoned/stalled streams free KV immediately."""
        loop = self._loop

        def _put():
            # a dropped client is DISCONNECTED: no further delivery (the
            # consumer drains what it had and gets the terminal
            # `disconnect` marker), so its later finish/sentinel can't
            # race the drain into looking like a normal end-of-stream
            if client.closed or client.dropped:
                return
            if ev is None:
                client.done = True
                with contextlib.suppress(asyncio.QueueFull):
                    client.q.put_nowait(None)
                return
            try:
                client.q.put_nowait(ev)
            except asyncio.QueueFull:
                client.dropped = True
                if client.srid is not None:
                    self._cmds.put(("cancel", client.srid, None, None))

        loop.call_soon_threadsafe(_put)

    # ---- async client surface (the in-process transport) --------------------

    async def submit(self, **kwargs) -> int:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._cmds.put(("submit", kwargs, None, fut))
        return await asyncio.wrap_future(fut)

    async def cancel(self, srid: int) -> bool:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._cmds.put(("cancel", srid, None, fut))
        return await asyncio.wrap_future(fut)

    async def open_stream(self, prompt, **kwargs
                          ) -> Tuple[int, ClientStream]:
        """Submit + attach a client pipe; returns ``(srid, stream)``.
        Raises what submit raises (queue full / draining / bad
        request)."""
        client = ClientStream(self.client_queue)
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._cmds.put(("submit", {"prompt": prompt, **kwargs}, client,
                        fut))
        srid = await asyncio.wrap_future(fut)
        return srid, client

    async def agenerate(self, prompt, **kwargs
                        ) -> AsyncIterator[Dict[str, Any]]:
        """The in-process streaming client: yields ``start`` / ``token``
        / ``finish`` (/ ``disconnect``) events. Abandoning the iterator
        (``aclose()``, ``break`` + GC, a vanished consumer) cancels the
        request — its KV blocks return to the pool immediately."""
        srid, client = await self.open_stream(prompt, **kwargs)
        finished = False
        try:
            yield {"type": "start", "rid": srid}
            async for ev in client.events():
                if ev.get("type") in ("finish", "disconnect"):
                    finished = True
                yield ev
        finally:
            client.closed = True
            self._open.pop(srid, None)
            if not finished:
                self._cmds.put(("cancel", srid, None, None))

    # ---- the one request handler (both transports) ---------------------------

    async def handle(self, method: str, path: str,
                     body: Optional[Dict[str, Any]] = None
                     ) -> Tuple[int, Any]:
        """Route one request. Returns ``(status, payload)`` where payload
        is a JSON-serializable dict, or ``("sse", async_iterator)`` for
        the streaming endpoint. The in-process transport calls this
        directly (port-free tier-1 path); the TCP transport serializes
        it."""
        if method == "GET" and path == "/healthz":
            alive = self._thread is not None and self._thread.is_alive()
            snap = self.sup.health_snapshot()
            ok = bool(alive and snap["ok"])
            return (200 if ok else 503), {
                "ok": ok, "pump_alive": alive,
                "pump_error": (str(self.pump_error)
                               if self.pump_error else None),
                "watchdog": snap["watchdog"]}
        if method == "GET" and path == "/readyz":
            snap = self.sup.health_snapshot()
            sup = snap["supervisor"]
            ready = bool(snap["accepting"])
            return (200 if ready else 503), {
                "ready": ready, "accepting": snap["accepting"],
                "draining": sup["draining"], "broken": sup["broken"],
                "restarts": sup["restarts"],
                "restart_budget": sup["restart_budget"],
                "retry_after_s": snap["retry_after_s"]}
        if method == "GET" and path == "/metrics":
            return 200, self.sup.health_snapshot()
        if method == "POST" and path == "/generate":
            body = dict(body or {})
            if "prompt" not in body:
                return 400, {"error": "missing 'prompt'"}
            try:
                gen = self.agenerate(body.pop("prompt"), **body)
                first = await gen.__anext__()       # surfaces submit errors
            except ServingUnavailable as e:
                return 503, {"error": str(e), "reason": e.reason,
                             "retry_after_s": e.retry_after_s}
            except ServingQueueFull as e:
                return 429, {"error": str(e), "reason": "shed",
                             "queue_depth": e.queue_depth,
                             "live_slots": e.live_slots,
                             "retry_after_s": e.retry_after_s}
            except (TypeError, ValueError) as e:
                return 400, {"error": str(e)}

            async def _stream():
                try:
                    yield first
                    async for ev in gen:
                        yield ev
                finally:
                    await gen.aclose()

            return 200, ("sse", _stream())
        return 404, {"error": f"no route {method} {path}"}

    # ---- TCP transport (HTTP/1.1 + SSE) --------------------------------------

    async def start_tcp(self, host: str = "127.0.0.1",
                        port: int = 0) -> int:
        """Serve :meth:`handle` over a real socket; returns the bound
        port. The tier-1 suite stays on the in-process transport — this
        path is covered by the slow tier and real deployments."""
        self._tcp = await asyncio.start_server(self._conn, host, port)
        self.port = self._tcp.sockets[0].getsockname()[1]
        return self.port

    async def _conn(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
        try:
            line = await reader.readline()
            if not line:
                return
            try:
                method, path, _ = line.decode().split(None, 2)
            except ValueError:
                return
            clen = 0
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                name, _, val = h.decode().partition(":")
                if name.strip().lower() == "content-length":
                    clen = int(val.strip() or 0)
            body = None
            if clen:
                raw = await reader.readexactly(clen)
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError:
                    body = None
            status, payload = await self.handle(method.upper(), path, body)
            reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                      429: "Too Many Requests",
                      503: "Service Unavailable"}.get(status, "OK")
            if isinstance(payload, tuple) and payload[0] == "sse":
                writer.write((f"HTTP/1.1 {status} {reason}\r\n"
                              "Content-Type: text/event-stream\r\n"
                              "Cache-Control: no-cache\r\n"
                              "Connection: close\r\n\r\n").encode())
                gen = payload[1]
                try:
                    async for ev in gen:
                        writer.write(sse_encode(ev))
                        await writer.drain()
                except (ConnectionResetError, BrokenPipeError):
                    pass                # client vanished mid-stream
                finally:
                    await gen.aclose()  # -> cancel if not finished
            else:
                data = json.dumps(payload).encode()
                extra = ""
                ra = isinstance(payload, dict) and \
                    payload.get("retry_after_s")
                if status in (429, 503) and ra:
                    extra = f"Retry-After: {max(1, int(round(ra)))}\r\n"
                writer.write((f"HTTP/1.1 {status} {reason}\r\n"
                              "Content-Type: application/json\r\n"
                              f"Content-Length: {len(data)}\r\n{extra}"
                              "Connection: close\r\n\r\n").encode())
                writer.write(data)
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        finally:
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()


def serve_requests(server: ServingServer, prompts,
                   **kwargs) -> Dict[str, Any]:
    """Synchronous convenience: serve a batch of prompts through the
    in-process transport on a private event loop — the 'mini trace
    through the server' entry the bench front-line row uses. Returns
    ``{"outputs": [token lists in submission order], "elapsed_s": serve
    wall time (drain excluded), "drain_report": close()'s report}``."""

    async def _run():
        outs = [None] * len(prompts)
        async with server.running():
            t0 = time.time()

            async def one(i):
                toks = []
                async for ev in server.agenerate(prompts[i], **kwargs):
                    if ev["type"] == "token":
                        toks.append(ev["token"])
                outs[i] = toks

            await asyncio.gather(*(one(i) for i in range(len(prompts))))
            elapsed = time.time() - t0
        return outs, elapsed

    outs, elapsed = asyncio.run(_run())
    return {"outputs": outs, "elapsed_s": elapsed,
            "drain_report": server.drain_report}
