"""Engine supervision: crash barrier, restart budget, graceful drain,
TPOT/autoscale telemetry (docs/OPS.md "Serving front line").

A replica that loses its engine loses every in-flight request; a replica
that cannot stop admitting while it finishes in-flight work turns every
deploy/preemption into an error storm. :class:`EngineSupervisor` closes
both gaps around :class:`~.engine.ServingEngine`:

* **Crash barrier.** ``step()`` runs the engine iteration under a
  try/except: an unexpected exception (or a global
  :mod:`~paddle_tpu.health.watchdog` trip whose diagnosis names a
  ``serving.*`` section) tears the engine down, rebuilds it from the SAME
  params/config — reusing the dead engine's compiled
  :class:`~.engine.EnginePrograms`, so recovery never recompiles — and
  **re-submits** every non-terminal request: queued requests verbatim,
  running ones from ``prompt + tokens so far`` riding the
  preemption-recompute path (:meth:`~.engine.ServingEngine.resubmit`), so
  greedy outputs stay bit-identical to an uninterrupted run and no
  delivered token is ever repeated. A restart budget
  (``FLAGS_serving_max_restarts``) bounds the crash loop: once exhausted
  the replica flips to **not accepting** (``/readyz`` 503) and in-flight
  requests fail with their partial output readable.

* **Graceful drain.** SIGTERM (the launcher's preemption forward — see
  :meth:`install_signal_handler`) or :meth:`close` stops admissions
  (submits raise the structured :class:`ServingUnavailable` carrying
  ``retry_after_s``), finishes in-flight work within a deadline
  (``PADDLE_PREEMPT_GRACE`` minus margin when the launcher exported it,
  else ``FLAGS_serving_drain_deadline_s``), then cancels the remainder —
  exiting with zero pool blocks held.

* **Autoscale telemetry.** :func:`autoscale_signal` turns one health
  snapshot + the shed delta into a scale-up / scale-in / hold
  recommendation; :meth:`EngineSupervisor.autoscale_signal` tracks the
  delta between calls and can write the elastic launcher's
  ``--elastic_rejoin_file`` format
  (:func:`paddle_tpu.distributed.launch.main.write_rejoin_file`), closing
  the loop from queue-depth/shed-rate telemetry to actual capacity.

The supervisor is synchronous and thread-safe; the asyncio front line
(:mod:`.server`) drives it from a dedicated engine thread while the event
loop multiplexes clients.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...flags import flag
from ...health import watchdog as _watchdog
from .engine import ServingEngine
from .journal import RequestJournal
from .scheduler import (CANCELLED, FINISHED, QUEUED, TERMINAL_STATES,
                        completes_by_tokens)

__all__ = ["EngineSupervisor", "ServingUnavailable", "TrackedRequest",
           "autoscale_signal", "FAILED"]

# supervisor-only terminal state: the restart budget ran out with this
# request still in flight (its partial output stays readable)
FAILED = "failed"


class ServingUnavailable(RuntimeError):
    """The replica is not admitting — draining (a deploy/preemption is in
    progress) or broken (restart budget exhausted). The structured 503:
    ``reason`` plus a ``retry_after_s`` backoff hint a front end can
    serialize straight into the response."""

    def __init__(self, message: str, reason: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class TrackedRequest:
    """The supervisor's engine-independent view of one request: enough to
    re-create it verbatim on a fresh engine (the crash-recovery contract)
    plus the tokens already DELIVERED to the client — the resubmission
    resumes after them, never repeating one."""

    srid: int                          # supervisor rid: stable across
    #                                    restarts (engine rids are not)
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    tenant: Optional[str]
    priority: int
    deadline: Optional[float]          # absolute, like Request.deadline
    # RESOLVED sampling knobs (ISSUE 11): resubmission replays them
    # verbatim, and the per-token-index PRNG keys make the recovered
    # sampled stream bit-identical to an uninterrupted run
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    adapter_id: Optional[str] = None   # LoRA adapter (ISSUE 19); the
    #                                    resubmission re-selects it so the
    #                                    recovered stream runs the same
    #                                    adapted weights
    erid: int = -1                     # rid in the CURRENT engine
    jid: int = -1                      # journal record id (ISSUE 18);
    #                                    -1 = unjournaled/disowned
    tokens: List[int] = dataclasses.field(default_factory=list)
    state: str = QUEUED
    resubmits: int = 0
    finish: Optional[Dict[str, Any]] = None

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES or self.state == FAILED

    @property
    def finished_by_tokens(self) -> bool:
        """True when the delivered tokens alone complete the request
        (budget spent or EOS delivered) — a crash caught it finished but
        not yet swept; record it, don't resubmit it."""
        return completes_by_tokens(self.tokens, self.max_new_tokens,
                                   self.eos_token_id)


def install_drain_handler(target, signum: int = signal.SIGTERM):
    """Wire ``signum`` (SIGTERM: the elastic launcher's preemption
    forward) to ``target.request_drain()`` — the one signal-plumbing
    helper the supervisor and the router share. Returns ``(handler,
    previous_handler)``, or ``(None, None)`` off the main thread (the
    caller polls instead)."""

    def _handler(sig, frame):
        target.request_drain()

    try:
        prev = signal.signal(signum, _handler)
    except ValueError:                 # not the main thread
        return None, None
    return _handler, prev


def uninstall_drain_handler(prev, signum: int = signal.SIGTERM) -> None:
    if prev is None:
        return
    try:
        signal.signal(signum, prev)
    except ValueError:
        pass


def autoscale_signal(snapshot: Dict[str, Any], shed_delta: int = 0,
                     high_water: float = 0.5,
                     low_water: float = 0.25) -> Dict[str, Any]:
    """One scale recommendation from one health snapshot: ``scale_up``
    when load was shed since the last signal or the queue sits past
    ``high_water`` of its bound (the replica is the bottleneck),
    ``scale_in`` when the queue is empty and slot utilization is at or
    under ``low_water`` (capacity is idle), else ``hold``. Pure function
    of its inputs so a bench/autoscaler can drive it from any snapshot;
    :meth:`EngineSupervisor.autoscale_signal` adds the shed-delta
    tracking and the rejoin-file write."""
    queued = int(snapshot["queued"])
    limit = max(1, int(snapshot["queue_limit"]))
    live = int(snapshot["live_slots"])
    slots = max(1, int(snapshot["max_slots"]))
    pressure = queued / limit
    util = live / slots
    if shed_delta > 0:
        action = "scale_up"
        reason = f"shed {shed_delta} request(s) since the last signal"
    elif pressure >= high_water:
        action = "scale_up"
        reason = (f"queue {queued}/{limit} at or past the "
                  f"{high_water:.0%} high-water mark")
    elif queued == 0 and util <= low_water:
        action = "scale_in"
        reason = (f"idle: {live}/{slots} slots busy, queue empty "
                  f"(low-water {low_water:.0%})")
    else:
        action = "hold"
        reason = f"queue {queued}/{limit}, slots {live}/{slots}"
    return {"action": action, "reason": reason,
            "queue_pressure": round(pressure, 3),
            "utilization": round(util, 3),
            "shed_delta": int(shed_delta),
            "retry_after_s": snapshot.get("retry_after_s")}


class EngineSupervisor:
    """Crash-barrier + drain + telemetry wrapper around one
    :class:`ServingEngine`. Request ids returned by :meth:`submit` are
    SUPERVISOR ids — stable across engine restarts (engine rids are
    not)."""

    def __init__(self, params, model_config, serving_config=None,
                 gen_config=None, max_restarts: Optional[int] = None,
                 drain_deadline_s: Optional[float] = None, programs=None,
                 journal="unset", embed_model=None):
        self._params = params
        self._embed_model = embed_model
        # LoRA adapters registered through THIS supervisor (ISSUE 19):
        # host copies survive engine teardown, so every rebuild
        # re-registers them and crash recovery can resubmit adapter
        # traffic onto the fresh engine's pool
        self._adapter_registry: Dict[str, Any] = {}
        self._model_config = model_config
        self._serving_config = serving_config
        self._gen_config = gen_config
        self.max_restarts = int(max_restarts if max_restarts is not None
                                else flag("FLAGS_serving_max_restarts"))
        self.drain_deadline_s = float(
            drain_deadline_s if drain_deadline_s is not None
            else flag("FLAGS_serving_drain_deadline_s"))
        self._lock = threading.RLock()
        self.restarts = 0
        self.crashes: List[str] = []
        self.broken = False
        self.draining = False
        self.closed = False
        self.resubmitted = 0
        self.recovered_tokens = 0
        self.adopted = 0          # requests failed over FROM another replica
        self.migrated_in = 0      # adopted WITH their KV blocks (ISSUE 16)
        self.migrated_out = 0     # released here after a live migration
        self.completed = 0
        self._drain_requested = False
        self._prev_sigterm = None
        self._next_srid = 0
        self._reqs: Dict[int, TrackedRequest] = {}
        self._by_erid: Dict[int, TrackedRequest] = {}
        self._wd_seen: Optional[object] = None
        self._last_shed = 0
        self._programs = programs
        # durable serving (ISSUE 18): 'unset' resolves through
        # FLAGS_serving_journal_dir (empty = off); an explicit journal
        # instance (the router shares ONE across its replicas) or an
        # explicit None always wins over the flag.
        if isinstance(journal, str) and journal == "unset":
            jdir = str(flag("FLAGS_serving_journal_dir", ""))
            journal = RequestJournal(jdir) if jdir else None
        self._journal = journal
        self.engine = self._build_engine()
        # terminal TrackedRequests are retained BOUNDED (insertion order,
        # oldest evicted) — the scheduler's own record bound, which is
        # the most requests that can be in flight at once, so one
        # run()/drain cycle (and the router's per-step sweep) can always
        # collect results, while a long-lived replica cannot retain
        # every prompt it ever served
        self._keep_finished = self.engine._sched.keep_finished

    def _build_engine(self) -> ServingEngine:
        eng = ServingEngine(self._params, self._model_config,
                            self._serving_config, self._gen_config,
                            programs=self._programs,
                            journal=self._journal,
                            embed_model=self._embed_model)
        # reuse the first engine's compiled programs on every rebuild:
        # restart must never pay a recompile (EnginePrograms docstring)
        self._programs = eng.programs
        for name, aparams in self._adapter_registry.items():
            eng.register_adapter(name, aparams)
        return eng

    # ---- admission ---------------------------------------------------------

    @property
    def accepting(self) -> bool:
        """Whether a submit() right now would queue: not broken (restart
        budget intact), not draining/closed, and the engine's admission
        queue below its bound — the ``/readyz`` predicate."""
        with self._lock:
            return (not self.broken and not self.draining
                    and not self.closed
                    and len(self.engine._sched.queue)
                    < self.engine._sched.queue_depth)

    def _check_admitting(self) -> None:
        if self.broken:
            raise ServingUnavailable(
                f"replica broken: engine restart budget "
                f"({self.max_restarts}) exhausted; last crash: "
                f"{self.crashes[-1] if self.crashes else '?'}",
                reason="broken", retry_after_s=None)
        if self.draining or self.closed or self._drain_requested:
            raise ServingUnavailable(
                "replica draining: admissions stopped, in-flight work "
                "finishing; retry against another replica",
                reason="draining",
                retry_after_s=self.engine._sched.retry_after_s())

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = "unset",
               timeout_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None, priority: int = 0,
               temperature="unset", top_k="unset", top_p="unset",
               seed="unset", adapter_id: Optional[str] = None) -> int:
        """Queue one prompt; returns the SUPERVISOR request id (stable
        across engine restarts). Sampling knobs pass through to
        :meth:`ServingEngine.submit` (resolved once there — the tracked
        record mirrors the RESOLVED values so a crash resubmission
        replays them verbatim). Raises :class:`ServingUnavailable` while
        draining or broken (the structured 503) and passes
        :class:`~.scheduler.ServingQueueFull` through (the structured
        shed)."""
        with self._lock:
            self._check_admitting()
            erid = self.engine.submit(
                prompt, max_new_tokens=max_new_tokens,
                eos_token_id=eos_token_id, timeout_s=timeout_s,
                deadline_s=deadline_s, tenant=tenant, priority=priority,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed, adapter_id=adapter_id)
            return self._track(erid).srid

    def _track(self, erid: int, resubmits: int = 0) -> TrackedRequest:
        """Mirror the RESOLVED engine record (defaults, sentinels,
        deadline already applied by the one resolver,
        engine._make_request) into a TrackedRequest — the single place
        submit() and resubmit() register work, so a crash resubmission
        re-creates exactly what was queued."""
        req = self.engine._sched.find(erid)
        rec = TrackedRequest(
            srid=self._next_srid, prompt=req.prompt,
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id, tenant=req.tenant,
            priority=req.priority, deadline=req.deadline,
            temperature=req.temperature, top_k=req.top_k,
            top_p=req.top_p, seed=req.seed,
            adapter_id=req.adapter_id, erid=erid, jid=req.jid)
        rec.tokens = [int(t) for t in req.tokens]
        rec.resubmits = resubmits
        self._next_srid += 1
        self._reqs[rec.srid] = rec
        self._by_erid[rec.erid] = rec
        self._prune_records()
        return rec

    def _prune_records(self) -> None:
        """Evict the oldest TERMINAL records past the retention bound
        (live ones — still in ``_by_erid`` or FAILED-pending-collection
        within the bound — are never touched)."""
        excess = len(self._reqs) - len(self._by_erid) - self._keep_finished
        if excess > 0:
            for srid in list(self._reqs):
                if excess <= 0:
                    break
                if self._reqs[srid].terminal:
                    del self._reqs[srid]
                    excess -= 1

    def resubmit(self, prompt, tokens: Sequence[int] = (),
                 max_new_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = "unset",
                 deadline: Optional[float] = None,
                 tenant: Optional[str] = None, priority: int = 0,
                 temperature="unset", top_k="unset", top_p="unset",
                 seed="unset", jid: Optional[int] = None,
                 adapter_id: Optional[str] = None) -> int:
        """ADOPT a request recovered from another replica (the router's
        cross-replica failover): queue it with the tokens the client has
        already been delivered, riding :meth:`ServingEngine.resubmit`'s
        recompute path — greedy output stays bit-identical to an
        uninterrupted run and no delivered token is re-emitted. Bypasses
        the queue-depth shed (the work was already accepted once,
        somewhere) but still refuses while draining or broken. Returns
        the new supervisor rid."""
        with self._lock:
            self._check_admitting()
            erid = self.engine.resubmit(
                prompt, tokens, max_new_tokens=max_new_tokens,
                eos_token_id=eos_token_id, deadline=deadline,
                tenant=tenant, priority=priority, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed, jid=jid,
                adapter_id=adapter_id)
            rec = self._track(erid, resubmits=1)    # born from a failover
            self.adopted += 1
            self.recovered_tokens += len(rec.tokens)
            return rec.srid

    # ---- durable cold-restart recovery (ISSUE 18) --------------------------

    @property
    def journal(self) -> Optional[RequestJournal]:
        return self._journal

    @classmethod
    def recover(cls, journal_dir: str, params, model_config,
                serving_config=None, gen_config=None,
                max_restarts: Optional[int] = None,
                drain_deadline_s: Optional[float] = None, programs=None,
                journal: Optional[RequestJournal] = None,
                embed_model=None, adapters: Optional[Dict[str, Any]] = None
                ) -> "EngineSupervisor":
        """Rebuild a replica after a FULL process death from its journal
        directory: open the journal (newest good snapshot + WAL suffix,
        torn tail truncated), then for every record — terminal ones
        become readable tracked records; ones whose delivered tokens
        already complete them are closed FINISHED (record it, don't
        re-run it); every other request is resubmitted bit-exactly from
        prompt + delivered-so-far under its original jid, so the
        exactly-once ledger is primed from the journal and no delivered
        token is ever re-emitted. KV recomputes through the resubmit
        path, reusing whatever the prefix cache still holds. Idempotent:
        a second crash during recovery replays to the same state."""
        j = journal if journal is not None else RequestJournal(journal_dir)
        sup = cls(params, model_config, serving_config, gen_config,
                  max_restarts=max_restarts,
                  drain_deadline_s=drain_deadline_s, programs=programs,
                  journal=j, embed_model=embed_model)
        for name, aparams in (adapters or {}).items():
            sup.register_adapter(name, aparams)
        sup._restore_from_journal()
        return sup

    def _restore_from_journal(self) -> None:
        """Turn the journal's mirror into tracked requests + engine
        resubmissions (submission order — jids are allocated in it)."""
        j = self._journal
        if j is None:
            return
        with self._lock:
            for jid in sorted(j.records):
                rec = j.records[jid]
                tr = TrackedRequest(
                    srid=self._next_srid, prompt=rec.prompt_array(),
                    max_new_tokens=rec.max_new_tokens,
                    eos_token_id=rec.eos_token_id, tenant=rec.tenant,
                    priority=rec.priority, deadline=rec.deadline,
                    temperature=rec.temperature, top_k=rec.top_k,
                    top_p=rec.top_p, seed=rec.seed,
                    adapter_id=rec.adapter_id, jid=jid)
                tr.tokens = [int(t) for t in rec.tokens]
                self._next_srid += 1
                self._reqs[tr.srid] = tr
                if rec.terminal:
                    tr.state = rec.state
                    tr.finish = {"state": rec.state,
                                 "tokens": len(tr.tokens),
                                 "recovered": True, "resubmits": 0}
                    continue
                if tr.finished_by_tokens:
                    # died after its last delivered token but before the
                    # terminal event landed: it IS complete
                    tr.state = FINISHED
                    tr.finish = {"state": FINISHED,
                                 "tokens": len(tr.tokens),
                                 "recovered": True, "resubmits": 0}
                    self.completed += 1
                    j.log_terminal(jid, FINISHED)
                    continue
                if (tr.adapter_id is not None
                        and not self.engine.adapter_registered(
                            tr.adapter_id)):
                    # the journal outlived the adapter registry (weights
                    # live OUTSIDE the journal by design): fail the
                    # record readably instead of poisoning recovery
                    tr.state = FAILED
                    tr.finish = {"state": FAILED,
                                 "tokens": len(tr.tokens),
                                 "reason": (f"adapter {tr.adapter_id!r} "
                                            f"not registered at recovery"),
                                 "recovered": True, "resubmits": 0}
                    j.log_terminal(jid, FAILED)
                    continue
                tr.erid = self.engine.resubmit(
                    tr.prompt, tr.tokens,
                    max_new_tokens=tr.max_new_tokens,
                    eos_token_id=tr.eos_token_id, deadline=tr.deadline,
                    tenant=tr.tenant, priority=tr.priority,
                    temperature=tr.temperature, top_k=tr.top_k,
                    top_p=tr.top_p, seed=tr.seed, jid=jid,
                    adapter_id=tr.adapter_id)
                tr.state = QUEUED
                tr.resubmits = 1
                self.resubmitted += 1
                self.recovered_tokens += len(tr.tokens)
                self._by_erid[tr.erid] = tr
            j.flush()
            self._prune_records()

    def disown_journal(self, srid: int) -> None:
        """Detach a live request from its journal record (see
        :meth:`ServingEngine.journal_disown`) — the router calls this
        before deliberately cancelling a copy whose logical request
        lives on elsewhere (hedges, evacuation-with-failover)."""
        with self._lock:
            rec = self._reqs.get(srid)
            if rec is None or rec.terminal:
                return
            self.engine.journal_disown(rec.erid)
            rec.jid = -1

    def journal_own(self, srid: int, jid: int, tokens) -> bool:
        """Attach a live request to journal record ``jid``, rebasing its
        delivered cursor to ``tokens`` (hedge promotion — see
        :meth:`ServingEngine.journal_own`)."""
        with self._lock:
            rec = self._reqs.get(srid)
            if rec is None or rec.terminal:
                return False
            if not self.engine.journal_own(rec.erid, jid, tokens):
                return False
            rec.jid = int(jid)
            return True

    # ---- live KV migration (ISSUE 16) --------------------------------------

    def export_request(self, srid: int):
        """Serialize one in-flight request — resolved record + computed
        KV blocks — for live migration to another replica (the router's
        drain/roll/scale-in path). Returns the portable payload, or None
        when the request is terminal or already finished (the origin's
        own drain will deliver it; migrating would re-run it). The
        origin keeps serving the request until :meth:`release_migrated`
        confirms the adoption."""
        with self._lock:
            rec = self._reqs.get(srid)
            if rec is None or rec.terminal:
                return None
            return self.engine.serialize_request(rec.erid)

    def adopt(self, payload) -> int:
        """ADOPT a live-migrated request: restore its KV blocks into this
        replica's pool and resume it exactly where the origin paused it —
        ``recomputed_tokens == 0``, bit-identical stream (the
        :meth:`ServingEngine.adopt` contract). Raises
        :class:`~.engine.AdoptError` when this replica cannot take the
        blocks (pool full, slot shortage, TP/layout mismatch) — the
        router falls back to the resubmit/recompute path — and
        :class:`ServingUnavailable` while draining or broken. Returns
        the new supervisor rid."""
        with self._lock:
            self._check_admitting()
            erid = self.engine.adopt(payload)
            rec = self._track(erid, resubmits=1)    # born from a migration
            self.adopted += 1
            self.migrated_in += 1
            self.recovered_tokens += len(rec.tokens)
            return rec.srid

    # ---- fleet-wide cache pulls (ISSUE 17) ---------------------------------

    def export_chain(self, chain):
        """Serialize a cached prefix chain (no request attached) for a
        cross-replica cache pull — :meth:`ServingEngine.export_chain`
        guarded for a dead/rebuilding engine. None when the engine is
        unavailable or holds none of the chain (a stale directory entry
        — the benign miss; the puller recomputes)."""
        with self._lock:
            if self.broken or self.engine is None:
                return None
            return self.engine.export_chain(chain)

    def graft_chain(self, payload):
        """Land an exported chain in this replica's prefix cache —
        :meth:`ServingEngine.graft_chain` guarded for availability.
        Raises :class:`ServingUnavailable` while draining or broken and
        :class:`~.engine.AdoptError` on layout mismatch; both degrade
        the pull to plain recompute at the router."""
        with self._lock:
            self._check_admitting()
            return self.engine.graft_chain(payload)

    def release_migrated(self, srid: int) -> bool:
        """Confirm a migration: the adoptive replica owns the request
        now, so cancel the origin's copy (frees its blocks — possibly
        into the offload tier) and mark the record migrated so no sweep
        treats it as lost work. Idempotent."""
        with self._lock:
            rec = self._reqs.get(srid)
            if rec is None:
                return False
            already = rec.terminal
            if not already:
                # the adoptive replica owns the journal record now: the
                # vacated copy must not mark the logical request terminal
                self.engine.journal_disown(rec.erid)
                rec.jid = -1
                self.engine.cancel(rec.erid)
                self._sweep()
                self.migrated_out += 1
            if rec.finish is not None:
                rec.finish["migrated"] = True
            return not already

    # ---- multi-adapter LoRA + embeddings (ISSUE 19) ------------------------

    def register_adapter(self, name: str, adapter_params) -> None:
        """Register a LoRA adapter on the live engine AND in the
        supervisor's host registry, so every crash rebuild re-registers
        it (weights survive the engine; residency/pins do not — a
        recovered request re-faults its adapter in through the pool's
        normal load path)."""
        with self._lock:
            self.engine.register_adapter(name, adapter_params)
            self._adapter_registry[str(name)] = adapter_params

    def adapter_registered(self, name: str) -> bool:
        with self._lock:
            return self.engine.adapter_registered(name)

    def adapter_resident(self, name: str) -> bool:
        """Device residency of one adapter — the router's affinity
        signal (False on a broken replica: nothing is resident)."""
        with self._lock:
            if self.broken:
                return False
            return self.engine.adapter_resident(name)

    def adapter_partition(self):
        with self._lock:
            return self.engine.adapter_partition()

    def submit_embedding(self, prompt, timeout_s: Optional[float] = None,
                         deadline_s: Optional[float] = None,
                         tenant: Optional[str] = None,
                         priority: int = 0) -> int:
        """Queue a prefill-only embedding request; returns the ENGINE
        rid (embeddings are stateless and unjournaled — they retire
        within the admitting step, so the supervisor does not track
        them; a crash mid-batch simply drops them and the client
        retries)."""
        with self._lock:
            self._check_admitting()
            return self.engine.submit_embedding(
                prompt, timeout_s=timeout_s, deadline_s=deadline_s,
                tenant=tenant, priority=priority)

    def embedding(self, erid: int):
        """Pooled embedding row, or ``None`` while the request is still
        queued/in-flight (the engine raises KeyError until it retires —
        the router polls this against ``is not None``)."""
        with self._lock:
            try:
                return self.engine.embedding(erid)
            except KeyError:
                return None

    def depth(self) -> int:
        """Queued + live requests on this replica — the router's
        power-of-two-choices load signal. A broken replica reports a
        depth no router should ever pick."""
        with self._lock:
            if self.broken:
                return 1 << 30
            return self.engine.depth()

    def cancel(self, srid: int) -> bool:
        """Cancel by supervisor rid; same idempotence contract as
        :meth:`ServingEngine.cancel`."""
        with self._lock:
            rec = self._reqs.get(srid)
            if rec is None or rec.terminal:
                return False
            ok = self.engine.cancel(rec.erid)
            self._sweep()
            return ok

    # ---- the supervised step loop ------------------------------------------

    def step(self, max_iters: Optional[int] = None) -> Dict[int, List[int]]:
        """One engine iteration under the crash barrier. Returns
        ``{srid: [tokens emitted]}``. An engine exception (or a serving
        hang-watchdog trip) triggers recovery — teardown, rebuild,
        resubmit — and returns ``{}`` for that iteration; past the
        restart budget the replica flips to broken instead."""
        with self._lock:
            if self.broken:
                return {}
            try:
                emitted = self.engine.step(max_iters)
            except Exception as e:                # noqa: BLE001 — barrier
                self._recover(f"engine step raised "
                              f"{type(e).__name__}: {e}")
                return {}
            if self._watchdog_tripped():
                self._recover("hang watchdog fired inside a serving "
                              "section")
                return {}
            out: Dict[int, List[int]] = {}
            for erid, toks in emitted.items():
                rec = self._by_erid.get(erid)
                if rec is None:
                    continue
                rec.tokens.extend(int(t) for t in toks)
                out[rec.srid] = [int(t) for t in toks]
            self._sweep()
            return out

    @property
    def pending(self) -> bool:
        with self._lock:
            return (not self.broken) and self.engine.pending

    def _watchdog_tripped(self) -> bool:
        """A fired global watchdog whose diagnosis names a ``serving.*``
        section means OUR dispatch hung (and has now, evidently,
        returned): treat it like a crash. Other sections are someone
        else's problem. Either way the trip is consumed once — a fresh
        watchdog is reinstalled so liveness detection survives the
        restart (a fired watchdog stands down)."""
        wd = _watchdog.current()
        if wd is None or not wd.fired.is_set() or wd is self._wd_seen:
            return False
        self._wd_seen = wd
        if "serving." not in (wd.diagnosis or ""):
            return False
        _watchdog.install(wd.timeout)
        return True

    def _sweep(self) -> None:
        """Mirror engine-terminal transitions into the tracked records:
        authoritative tokens/state come from the engine's finished record
        so cancel/timeout partials land exactly once."""
        fin = self.engine._sched.finished
        for erid in [e for e in self._by_erid if e in fin]:
            rec = self._by_erid.pop(erid)
            req = fin[erid]
            rec.tokens = [int(t) for t in req.tokens]
            rec.state = req.state
            rec.finish = {
                "state": req.state, "tokens": len(req.tokens),
                "ttft_s": req.ttft_s, "tpot_s": req.tok_latency_s,
                "prefix_hit_tokens": req.prefix_hit_tokens,
                "preemptions": req.preemptions,
                "recomputed_tokens": req.recomputed_tokens,
                "oom_truncated": req.oom_truncated,
                "resubmits": rec.resubmits,
            }
            if req.state == FINISHED:
                self.completed += 1
        # belt and braces: a tracked erid neither live nor in `finished`
        # reached a terminal state whose record was FIFO-evicted before
        # this sweep (the retention bound is sized so this cannot happen,
        # but a stuck stream + a later resubmission of cancelled work is
        # too costly to ever risk) — close it from the supervisor's view
        live = {r.rid for r in self.engine._sched.queue}
        live.update(r.rid for r in self.engine._sched.live)
        for erid in [e for e in self._by_erid if e not in live]:
            rec = self._by_erid.pop(erid)
            rec.state = FINISHED if rec.finished_by_tokens else CANCELLED
            rec.finish = {"state": rec.state, "tokens": len(rec.tokens),
                          "evicted_record": True,
                          "resubmits": rec.resubmits}
            if rec.state == FINISHED:
                self.completed += 1
        self._prune_records()

    def _recover(self, reason: str) -> None:
        self.crashes.append(reason)
        survivors = sorted(self._by_erid.values(), key=lambda r: r.srid)
        self._by_erid = {}
        # carry the drain deadline across the rebuild so a crash mid-
        # drain keeps reporting the true remaining window
        drain_deadline = self.engine._sched.drain_deadline
        if self.restarts >= self.max_restarts:
            # budget exhausted: flip to not-accepting instead of crash-
            # looping. In-flight requests FAIL (partial output readable);
            # a fresh idle engine keeps the ops surface readable without
            # trusting the dead engine's torn state.
            self.broken = True
            for rec in survivors:
                rec.state = FAILED
                rec.finish = {"state": FAILED, "tokens": len(rec.tokens),
                              "reason": reason,
                              "resubmits": rec.resubmits}
                if self._journal is not None and rec.jid >= 0:
                    self._journal.log_terminal(rec.jid, FAILED)
            if self._journal is not None:
                self._journal.flush()
            self.engine = self._build_engine()
            self.engine._sched.drain_deadline = drain_deadline
            return
        self.restarts += 1
        self.engine = self._build_engine()
        self.engine._sched.drain_deadline = drain_deadline
        for rec in survivors:
            if rec.finished_by_tokens:
                # crashed after its last token but before the retire
                # sweep: it IS complete — record it, don't re-run it
                rec.state = FINISHED
                rec.finish = {"state": FINISHED,
                              "tokens": len(rec.tokens),
                              "resubmits": rec.resubmits}
                self.completed += 1
                if self._journal is not None and rec.jid >= 0:
                    self._journal.log_terminal(rec.jid, FINISHED)
                continue
            rec.erid = self.engine.resubmit(
                rec.prompt, rec.tokens,
                max_new_tokens=rec.max_new_tokens,
                eos_token_id=rec.eos_token_id, deadline=rec.deadline,
                tenant=rec.tenant, priority=rec.priority,
                temperature=rec.temperature, top_k=rec.top_k,
                top_p=rec.top_p, seed=rec.seed, jid=rec.jid,
                adapter_id=rec.adapter_id)
            rec.resubmits += 1
            rec.state = QUEUED
            self.resubmitted += 1
            self.recovered_tokens += len(rec.tokens)
            self._by_erid[rec.erid] = rec
        if self._journal is not None:
            self._journal.flush()

    # ---- requests ----------------------------------------------------------

    def request(self, srid: int) -> TrackedRequest:
        with self._lock:
            return self._reqs[srid]

    def result(self, srid: int) -> np.ndarray:
        with self._lock:
            return np.asarray(self._reqs[srid].tokens, np.int32)

    def run(self, prompts: Sequence, max_new_tokens=None,
            eos_token_id="unset") -> List[np.ndarray]:
        """Submit every prompt, drive the supervised loop to drain,
        return outputs in submission order (the engine ``run()`` contract
        with the crash barrier around every step)."""
        n = len(prompts)
        mnt = ([max_new_tokens] * n
               if max_new_tokens is None or np.isscalar(max_new_tokens)
               else list(max_new_tokens))
        srids = [self.submit(p, max_new_tokens=m, eos_token_id=eos_token_id)
                 for p, m in zip(prompts, mnt)]
        while self.pending:
            self.step()
        return [self.result(s) for s in srids]

    # ---- graceful drain ----------------------------------------------------

    def request_drain(self) -> None:
        """Thread/signal-safe drain trigger: admissions stop immediately
        (submit raises the structured 503); whoever owns the step loop —
        :meth:`drain` here, or the server's pump thread — finishes the
        in-flight work within the deadline. Stamps the scheduler's
        ``drain_deadline`` so the structured 503's ``retry_after_s``
        reports the REMAINING drain window, not a cold-start estimate
        (whoever runs the actual :meth:`drain` re-stamps the final
        deadline)."""
        self._drain_requested = True
        # single attribute store — safe from a signal handler, no lock
        self.engine._sched.drain_deadline = (time.time()
                                             + self.drain_deadline_s)

    @property
    def drain_requested(self) -> bool:
        return self._drain_requested

    def install_signal_handler(self, signum: int = signal.SIGTERM):
        """Wire SIGTERM — the signal the elastic launcher forwards on
        preemption — to :meth:`request_drain`. When the launcher exported
        ``PADDLE_PREEMPT_GRACE``, the drain deadline tightens to that
        window minus a 2s margin (the same contract
        ``elastic.install_preemption_handler`` applies to emergency
        checkpoints). Returns the handler, or None off the main
        thread."""
        grace = os.environ.get("PADDLE_PREEMPT_GRACE")
        if grace is not None:
            try:
                self.drain_deadline_s = max(1.0, float(grace) - 2.0)
            except ValueError:
                pass
        handler, prev = install_drain_handler(self, signum)
        if handler is not None:
            self._prev_sigterm = prev
        return handler

    def uninstall_signal_handler(self, signum: int = signal.SIGTERM):
        uninstall_drain_handler(self._prev_sigterm, signum)
        self._prev_sigterm = None

    def drain(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        """Stop admissions, finish in-flight work within the deadline,
        cancel the remainder. Returns the drain report: completed /
        cancelled during the drain, wall time, and ``leaked_blocks``
        (must be 0 — every terminal path frees its KV)."""
        t0 = time.time()
        with self._lock:
            self.draining = True
            self._drain_requested = True
            done_before = self.completed
        deadline = t0 + (deadline_s if deadline_s is not None
                         else self.drain_deadline_s)
        with self._lock:
            self.engine._sched.drain_deadline = deadline
        while time.time() < deadline and self.pending:
            self.step()
        cancelled = 0
        with self._lock:
            if not self.broken and self.engine.pending:
                cancelled = self.engine.cancel_all()
                self._sweep()
            if self._journal is not None:
                # the SIGTERM/preemption grace contract: before the
                # process exits, the journal is flushed and a final
                # snapshot written, so a cold restart replays nothing
                # and every terminal state reached during the drain
                # (including the deadline cancels above) is durable
                self._journal.snapshot()
            leaked = self.engine.cache.manager.blocks_in_use
            report = {"completed": self.completed - done_before,
                      "cancelled": cancelled,
                      "leaked_blocks": int(leaked),
                      "duration_s": round(time.time() - t0, 3)}
        return report

    def close(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        report = self.drain(deadline_s)
        with self._lock:
            self.closed = True
        return report

    # ---- telemetry ---------------------------------------------------------

    def autoscale_signal(self, rejoin_file: Optional[str] = None,
                         workers: Optional[int] = None) -> Dict[str, Any]:
        """The scale recommendation for the CURRENT snapshot, with the
        shed delta tracked between calls (an autoscaler polls this, so
        "shed since last poll" is the rate signal it wants). With
        ``rejoin_file`` given, a scale-up also writes the elastic
        launcher's ``--elastic_rejoin_file`` signal (``workers`` = the
        offered count; None = "take what you need") so a standby launcher
        scales the job out."""
        with self._lock:
            snap = self.engine._health_snapshot_locked()
            shed = snap["counters"]["shed"]
            delta = shed - self._last_shed
            self._last_shed = shed
        sig = autoscale_signal(snap, shed_delta=delta)
        if rejoin_file and sig["action"] == "scale_up":
            from ...distributed.launch.main import write_rejoin_file
            write_rejoin_file(rejoin_file, workers)
            sig["rejoin_file"] = rejoin_file
        return sig

    def health_snapshot(self) -> Dict[str, Any]:
        """The engine's ops payload extended with the supervisor layer
        (``supervisor`` + ``autoscale`` fields — HEALTH_SNAPSHOT_FIELDS
        documents every key). ``accepting`` now folds in draining/broken,
        so ``/readyz`` can serve it directly."""
        with self._lock:
            snap = self.engine._health_snapshot_locked()
            snap["accepting"] = bool(
                snap["accepting"] and not self.broken
                and not self.draining and not self.closed
                and not self._drain_requested)
            snap["supervisor"] = {
                "restarts": self.restarts,
                "restart_budget": self.max_restarts,
                "broken": self.broken,
                "draining": bool(self.draining or self._drain_requested),
                "accepting": snap["accepting"],
                "resubmitted": self.resubmitted,
                "recovered_tokens": self.recovered_tokens,
                "adopted": self.adopted,
                "migrated_in": self.migrated_in,
                "migrated_out": self.migrated_out,
                "completed": self.completed,
                "crashes": list(self.crashes[-4:]),
            }
            # PEEK the shed delta, never consume it: /metrics and /readyz
            # GETs must not destroy the signal autoscale_signal() (the
            # rejoin-file writer) is built on — only that method advances
            # the baseline
            snap["autoscale"] = autoscale_signal(
                snap, shed_delta=snap["counters"]["shed"] - self._last_shed)
        return snap

    def block_partition(self) -> Dict[str, int]:
        """The engine's pool-partition view (free / evictable / in-use /
        usable) taken under this supervisor's lock — the accounting
        invariant the InvariantAuditor (audit.py) checks every step:
        free + evictable + in_use == usable."""
        with self._lock:
            return self.engine.block_partition()
