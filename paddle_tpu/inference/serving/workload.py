"""Deterministic workload generator + fleet-scale chaos replay driver
(docs/OPS.md "Workload replay & capacity planning").

Every bench row so far exercises ONE mechanism; nothing drove the whole
stack — router -> supervisors -> engines -> paged kernels — the way
production traffic would, with faults arriving mid-stream. This module
closes that gap with three composable pieces:

* **Deterministic workload generator.** :class:`WorkloadSpec` +
  :func:`generate_trace` emit a reproducible request stream keyed to
  engine-STEP indices (never wall-clock): diurnal/bursty arrival curves,
  Zipf-skewed tenants, Zipf-skewed multi-adapter LoRA mixes (a few hot
  adapters + a cold tail, exercising the paged adapter pool and the
  router's adapter affinity), shared-prefix prompt families (exercising
  the prefix cache and the router's prefix affinity), mixed greedy/sampled
  knobs, priorities and client-side deadlines, and client misbehavior —
  cancels, disconnect-mid-stream, abandoned streams, and duplicate
  retries after a 429/503 that BACK OFF by the returned
  ``retry_after_s`` before resubmitting. The trace is a pure function of
  the spec, so the spec IS the trace.

* **Replay manifest.** :class:`ReplayManifest` records the seed, the
  spec, the chaos-timeline schedule and the live ``FLAGS_serving_*``
  values. Any failure reproduces bit-exactly from the manifest: same
  per-request token streams, same chaos firing order, same audit trail
  (``retry_policy="fixed"`` — the deterministic backoff; ``"hint"``
  honors the measured wall-clock ``retry_after_s``, which is the
  production behavior but makes shed counts host-load-dependent).

* **Replay driver + capacity report.** :func:`run_replay` drives the
  trace through a multi-replica :class:`~.router.ServingRouter` with a
  seeded :class:`~paddle_tpu.testing.chaos.ChaosTimeline` interleaving
  the serving injectors mid-traffic while the autoscaler actuates
  (signal -> spawn/drain -> measured TTFT effect), the
  :class:`~.audit.InvariantAuditor` sampling throughout and running
  exhaustively at quiesce. The run emits a capacity-planning report
  (:func:`capacity_report`: ``paged_pool_block_bytes`` arithmetic across
  fp/int8 x TP degree plus the measured TTFT/TPOT percentile curves) and
  the ``serving_replay_goodput`` bench metric — SLO-met tokens per
  second per chip, the number the next perf PRs move.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...flags import get_flags
from .audit import InvariantAuditor
from .scheduler import FINISHED, ServingQueueFull
from .supervisor import FAILED, ServingUnavailable

__all__ = ["WorkloadSpec", "TraceRequest", "generate_trace",
           "ReplayManifest", "run_replay", "capacity_report"]


@dataclasses.dataclass
class WorkloadSpec:
    """Everything that determines a trace. JSON-serializable (tuples
    round-trip as lists), so a :class:`ReplayManifest` embeds it
    verbatim and two replays of one manifest generate identical traces.
    All times are engine-STEP indices — a replay never keys behavior to
    wall-clock."""

    requests: int = 200
    seed: int = 0
    vocab_size: int = 97
    # ---- arrivals ----
    horizon_steps: int = 0            # 0 = auto (~2 arrivals per step)
    arrival: str = "diurnal"          # diurnal | bursty | uniform
    diurnal_periods: float = 1.0      # peak/trough cycles over the horizon
    diurnal_amp: float = 0.9          # peak rate = (1+amp) x mean
    burstiness: float = 4.0           # bursty: in-burst rate multiplier
    burst_frac: float = 0.15          # fraction of the horizon in bursts
    # ---- request mix ----
    tenants: int = 6                  # Zipf-skewed tenant population
    zipf_alpha: float = 1.2
    families: int = 3                 # shared-prefix prompt families
    family_frac: float = 0.6          # requests opening with a family prefix
    prefix_len: int = 16              # family prefix tokens (block-align
    #                                   it so router affinity keys engage)
    tail_lens: Tuple[int, ...] = (2, 4, 6, 10)
    output_lens: Tuple[int, ...] = (2, 3, 4, 6, 12)   # long-tailed
    eos_token_id: Optional[int] = None
    sampled_frac: float = 0.25        # temperature/top-k/top-p rows
    priorities: Tuple[int, ...] = (0, 0, 0, 1, 2)
    deadline_frac: float = 0.2        # client-side step deadlines
    deadline_steps: Tuple[int, ...] = (60, 120, 240)
    # ---- client misbehavior ----
    misbehavior_frac: float = 0.08    # cancel / disconnect / abandon
    # ---- multi-adapter LoRA mix (ISSUE 19) ----
    # adapters=0 keeps the trace base-only AND rng-draw free: every
    # previously generated seed keeps its byte-identical trace. With
    # adapters>0 a Zipf-skewed adapter population rides the stream —
    # a few hot adapters dominating (the S-LoRA locality the router's
    # adapter affinity exploits) with a long cold tail (the churn the
    # device pool's LRU absorbs).
    adapters: int = 0                 # distinct adapters ("lora0"..)
    adapter_frac: float = 0.75        # requests carrying an adapter_id
    adapter_zipf_alpha: float = 1.2   # hot-adapter skew
    # ---- long-prompt mix (ISSUE 20) ----
    # long_prompt_frac=0 keeps the trace rng-draw free (byte-identical
    # old seeds). >0 extends that fraction of prompts with fresh tokens
    # up to ~long_prompt_len — prompts that must CHUNK through
    # prefill_chunk-sized pieces, the mid-flight-prefill pressure mixed
    # batching (FLAGS_serving_mixed_batch) absorbs into the decode
    # dispatch. Extension is appended at the prompt END so family
    # prefixes (and router affinity keys) stay intact.
    long_prompt_frac: float = 0.0     # requests stretched to ~long len
    long_prompt_len: int = 48         # target total prompt length
    # ---- 429/503 retry policy ----
    # "fixed": back off retry_backoff_steps engine steps per attempt —
    # deterministic, the replay-determinism contract's setting. "hint":
    # honor the response's retry_after_s against the wall clock (the
    # production client contract; shed counts then track host load).
    # "storm": resubmit immediately, ignoring the hint — the misbehaving
    # client the backoff regression test measures against.
    retry_policy: str = "fixed"
    retry_backoff_steps: int = 8
    max_attempts: int = 100
    # ---- driver knobs ----
    step_iters: int = 2               # decode iterations per driver step
    audit_every: int = 8              # structural audit sampling period
    #                                   (0 = only the exhaustive quiesce)
    autoscale_every: int = 16         # router.autoscale() polling period
    #                                   (0 = autoscaler off: the fixed-
    #                                   fleet counterfactual the bench
    #                                   row measures the p99 effect
    #                                   against)
    cooldown_steps: int = 48          # post-quiesce steps (scale-in lands)

    def __post_init__(self):
        if self.requests < 1:
            raise ValueError("requests must be >= 1")
        if self.arrival not in ("diurnal", "bursty", "uniform"):
            raise ValueError(f"unknown arrival curve {self.arrival!r}")
        if self.retry_policy not in ("fixed", "hint", "storm"):
            raise ValueError(f"unknown retry_policy {self.retry_policy!r}"
                             " (fixed | hint | storm)")
        if int(self.adapters) < 0:
            raise ValueError("adapters must be >= 0 (0 = base-only)")
        if int(self.retry_backoff_steps) < 1:
            raise ValueError(
                "retry_backoff_steps must be >= 1 (0 would re-bucket a "
                "shed client at the already-processed step and strand it)")
        for f in ("tail_lens", "output_lens", "priorities",
                  "deadline_steps"):
            setattr(self, f, tuple(int(x) for x in getattr(self, f)))

    @property
    def horizon(self) -> int:
        return int(self.horizon_steps) or max(8, self.requests // 2)

    def asdict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TraceRequest:
    """One generated client request, fully resolved (the trace is the
    contract — the driver never rolls dice)."""

    tid: int
    arrival_step: int
    tenant: str
    prompt: np.ndarray
    max_new_tokens: int
    family: Optional[int] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    priority: int = 0
    eos_token_id: Optional[int] = None
    deadline_steps: Optional[int] = None
    behavior: str = "normal"          # normal | cancel | disconnect | abandon
    behavior_at: int = 0              # delivered tokens before it fires
    adapter_id: Optional[str] = None  # None = base-model traffic


def _arrival_weights(spec: WorkloadSpec, rng) -> np.ndarray:
    H = spec.horizon
    s = np.arange(H, dtype=np.float64)
    if spec.arrival == "uniform":
        w = np.ones(H)
    elif spec.arrival == "diurnal":
        # trough at step 0, peak mid-horizon: the replay sees ramp-up,
        # saturation (autoscale's scale-up window) and ramp-down
        # (its scale-in window) in one pass
        w = 1.0 + spec.diurnal_amp * np.sin(
            2 * math.pi * spec.diurnal_periods * s / H - math.pi / 2)
    else:                                             # bursty
        w = np.ones(H)
        n_bursts = max(1, int(round(H * spec.burst_frac / 8)))
        for _ in range(n_bursts):
            at = rng.integers(0, max(1, H - 8))
            w[at:at + 8] *= spec.burstiness
    w = np.clip(w, 1e-3, None)
    return w / w.sum()


def generate_trace(spec: WorkloadSpec) -> List[TraceRequest]:
    """The seeded trace: a pure function of the spec, sorted by arrival
    step (ties by tid). Prompts for one family share a ``prefix_len``
    token prefix — sized to the serving block size, that is exactly the
    unit the prefix cache registers and the router's affinity key hashes."""
    rng = np.random.default_rng(int(spec.seed))
    w = _arrival_weights(spec, rng)
    arrivals = np.sort(rng.choice(spec.horizon, size=spec.requests, p=w))
    zipf = 1.0 / np.power(np.arange(1, spec.tenants + 1), spec.zipf_alpha)
    zipf /= zipf.sum()
    prefixes = [rng.integers(0, spec.vocab_size,
                             (spec.prefix_len,)).astype(np.int32)
                for _ in range(max(1, spec.families))]
    fam_w = 1.0 / np.power(np.arange(1, len(prefixes) + 1), spec.zipf_alpha)
    fam_w /= fam_w.sum()
    ad_w = None
    if spec.adapters > 0:
        ad_w = 1.0 / np.power(np.arange(1, spec.adapters + 1),
                              spec.adapter_zipf_alpha)
        ad_w /= ad_w.sum()
    out: List[TraceRequest] = []
    for tid in range(spec.requests):
        tenant = f"t{int(rng.choice(spec.tenants, p=zipf))}"
        fam = None
        tail = rng.integers(0, spec.vocab_size,
                            (int(rng.choice(spec.tail_lens)),)
                            ).astype(np.int32)
        if rng.random() < spec.family_frac:
            fam = int(rng.choice(len(prefixes), p=fam_w))
            prompt = np.concatenate([prefixes[fam], tail])
        else:
            prompt = np.concatenate(
                [rng.integers(0, spec.vocab_size, (2,)).astype(np.int32),
                 tail])
        tr = TraceRequest(
            tid=tid, arrival_step=int(arrivals[tid]), tenant=tenant,
            prompt=prompt, family=fam,
            max_new_tokens=int(rng.choice(spec.output_lens)),
            priority=int(rng.choice(spec.priorities)),
            eos_token_id=spec.eos_token_id)
        if rng.random() < spec.sampled_frac:
            tr.temperature = round(float(rng.uniform(0.3, 1.2)), 3)
            tr.top_k = int(rng.integers(2, 40))
            tr.top_p = round(float(rng.uniform(0.6, 1.0)), 3)
            tr.seed = int(rng.integers(0, 1 << 20))
        if rng.random() < spec.deadline_frac:
            tr.deadline_steps = int(rng.choice(spec.deadline_steps))
        if rng.random() < spec.misbehavior_frac:
            tr.behavior = str(rng.choice(["cancel", "disconnect",
                                          "abandon"]))
            tr.behavior_at = int(rng.integers(1, 4))
        # gated LAST so adapters=0 specs draw nothing here and every
        # previously generated seed keeps its byte-identical trace
        if spec.adapters > 0 and rng.random() < spec.adapter_frac:
            tr.adapter_id = \
                f"lora{int(rng.choice(spec.adapters, p=ad_w))}"
        # also gated LAST (after the adapter draw) for the same reason:
        # long_prompt_frac=0 draws nothing, old seeds stay byte-identical
        if spec.long_prompt_frac > 0 and \
                rng.random() < spec.long_prompt_frac:
            ext = int(spec.long_prompt_len) - len(tr.prompt)
            if ext > 0:
                tr.prompt = np.concatenate(
                    [tr.prompt,
                     rng.integers(0, spec.vocab_size,
                                  (ext,)).astype(np.int32)])
        out.append(tr)
    return out


@dataclasses.dataclass
class ReplayManifest:
    """Everything a bit-exact reproduction needs: the workload spec, the
    chaos schedule, and the serving flags in force. Emitted with every
    replay (and stamped into each :class:`~.audit.InvariantViolation`),
    so 'it failed at fleet scale' always comes with 'run THIS to see it
    again'."""

    spec: Dict[str, Any]
    chaos: List[Any]
    flags: Dict[str, Any]
    # the engine + fleet shape the run actually used: the resolved
    # ServingConfig / RouterConfig scalar fields + the starting replica
    # count — run_replay(manifest=) re-applies all three (unless the
    # caller overrides), because admission / shed / preemption /
    # breaker / autoscale behavior depends on them and a reproduction
    # with a different queue_depth or max_replicas is not a
    # reproduction. ``flags`` is the operator's reference record of the
    # FLAGS_serving_* environment; it is NOT auto-applied (both configs
    # resolved from it eagerly, so the shape fields already carry the
    # values that mattered).
    serving: Dict[str, Any] = dataclasses.field(default_factory=dict)
    router: Dict[str, Any] = dataclasses.field(default_factory=dict)
    replicas: int = 0
    version: int = 1

    @staticmethod
    def _scalars(config) -> Dict[str, Any]:
        # ServingConfig/RouterConfig resolve their flag-backed fields
        # eagerly at construction, so the scalar fields ARE the shape;
        # non-scalar leftovers (cache_dtype objects) re-resolve from
        # defaults at replay
        return {k: v for k, v in
                sorted(dataclasses.asdict(config).items())
                if isinstance(v, (bool, int, float, str)) or v is None}

    @classmethod
    def capture(cls, spec: WorkloadSpec, timeline=None,
                serving_config=None, router_config=None,
                replicas: int = 0) -> "ReplayManifest":
        flags = {k: v for k, v in sorted(get_flags().items())
                 if k.startswith("FLAGS_serving_")
                 and isinstance(v, (int, float, str, bool))}
        return cls(spec=spec.asdict(),
                   chaos=timeline.spec() if timeline is not None else [],
                   flags=flags,
                   serving=(cls._scalars(serving_config)
                            if serving_config is not None else {}),
                   router=(cls._scalars(router_config)
                           if router_config is not None else {}),
                   replicas=int(replicas))

    def workload(self) -> WorkloadSpec:
        return WorkloadSpec(**self.spec)

    def timeline(self):
        from ...testing.chaos import ChaosTimeline
        return ChaosTimeline.from_spec(self.chaos)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ReplayManifest":
        return cls(**json.loads(s))

    @property
    def tag(self) -> str:
        """Short stable identifier (what violations carry)."""
        return (f"replay seed={self.spec.get('seed')} "
                f"requests={self.spec.get('requests')} "
                f"crc={zlib.crc32(self.to_json().encode()):08x}")

    def __str__(self) -> str:
        return self.tag


class _Client:
    """Driver-side state for one trace request: submission attempts,
    retry backoff, the delivered-token stream, and the misbehavior
    script."""

    __slots__ = ("tr", "state", "next_step", "backoff_until", "attempts",
                 "retries", "frid", "delivered", "submit_step",
                 "first_step", "finish_step", "submit_t", "first_t",
                 "finish_t", "outcome", "behavior_fired")

    def __init__(self, tr: TraceRequest):
        self.tr = tr
        self.state = "waiting"        # waiting | backoff | live | done
        self.next_step = tr.arrival_step
        self.backoff_until = None     # wall-clock stamp (hint policy)
        self.attempts = 0
        self.retries = 0
        self.frid = None
        self.delivered: List[int] = []
        self.submit_step = None
        self.first_step = None
        self.finish_step = None
        self.submit_t = None
        self.first_t = None
        self.finish_t = None
        self.outcome = None           # finished | cancelled | deadline |
        #                               disconnected | gave_up | failed
        self.behavior_fired = False


def _pct(xs, q):
    return round(float(np.percentile(np.asarray(xs, np.float64), q)), 4) \
        if len(xs) else None


def run_replay(params, model_config, spec: Optional[WorkloadSpec] = None,
               manifest: Optional[ReplayManifest] = None,
               serving_config=None, router_config=None,
               replicas: Optional[int] = None,
               chaos: Any = "auto", chaos_events: int = 6,
               prefill_replicas: int = 0,
               programs=None, router=None, collect_violations: bool = False,
               record_streams: bool = False, hbm_gb: float = 16.0,
               host_gb: float = 0.0,
               max_steps: Optional[int] = None) -> Dict[str, Any]:
    """Drive one generated trace through a multi-replica router under a
    seeded chaos timeline, auditing throughout. Returns the replay
    report (counters, percentile curves, chaos log, autoscale log, the
    auditor digest, the capacity report and the manifest).

    Pass ``manifest=`` to REPLAY a previous run bit-exactly (spec and
    chaos schedule come from it); pass ``router=`` to replay onto an
    existing (e.g. rebuilt-from-shared-programs) fleet — the caller then
    owns its lifecycle. By default violations RAISE
    (:class:`~.audit.InvariantViolation` naming check/replica/manifest);
    ``collect_violations=True`` switches to the production spelling —
    everything runs, the report carries the list."""
    from ...testing.chaos import chaos_timeline as _mk_timeline
    from ...testing import chaos as _chaos
    from .engine import ServingConfig
    from .router import RouterConfig, ServingRouter

    fresh_manifest = manifest is None
    if manifest is not None:
        spec = manifest.workload()
        timeline = manifest.timeline()
        # reproduce the captured ENGINE + FLEET SHAPE too (admission/
        # shed/preemption/breaker/autoscale behavior depends on them),
        # unless the caller overrides
        if serving_config is None and manifest.serving:
            serving_config = ServingConfig(**manifest.serving)
        if router_config is None and manifest.router:
            router_config = RouterConfig(**manifest.router)
        if replicas is None and manifest.replicas:
            replicas = manifest.replicas
    else:
        spec = spec or WorkloadSpec()
        if chaos == "auto":
            timeline = _mk_timeline(spec.seed + 1, spec.horizon,
                                    events=chaos_events)
        elif chaos in (None, False):
            timeline = _mk_timeline(spec.seed + 1, spec.horizon, events=0)
        else:
            timeline = chaos
    if replicas is None:
        replicas = 3

    own_router = router is None
    if own_router:
        if serving_config is None:
            # a LoRA-mixed trace needs an adapter pool; size the device
            # slots BELOW the adapter population so the replay exercises
            # LRU eviction + reload under traffic, not just residency
            serving_config = ServingConfig(
                lora_slots=max(2, (spec.adapters + 1) // 2),
                lora_pool=max(16, spec.adapters)) \
                if spec.adapters > 0 else ServingConfig()
        if router_config is None:
            # deterministic fleet defaults: hedging off (wall-clock
            # race), breaker cooldown 0 (an opened breaker half-open
            # probes on the next routing pass instead of after a
            # wall-clock cooldown), probe caching off
            # prefill_replicas adds a disaggregated prefill pool (ISSUE
            # 17) — captured in the manifest like every other RouterConfig
            # scalar, so a replay rebuilds the same split fleet
            router_config = RouterConfig(replicas=replicas,
                                         breaker_cooldown_s=0.0,
                                         hedge_ttft_mult=0.0,
                                         prefill_replicas=prefill_replicas)
        router = ServingRouter(params, model_config, serving_config,
                               router_config=router_config,
                               programs=programs)
    tp = int(router.decode_config.tp)
    if spec.adapters > 0:
        # the trace's adapter population, seeded off the spec so a
        # replay regenerates identical adapter weights; scale well above
        # init-noise so adapter outputs genuinely diverge from base
        from ...models.lora import lora_init_params
        rank = int(router.decode_config.lora_rank)
        for i in range(int(spec.adapters)):
            name = f"lora{i}"
            if not router.adapter_registered(name):
                router.register_adapter(
                    name, lora_init_params(model_config, rank,
                                           seed=int(spec.seed) * 1000 + i,
                                           scale=0.5))
    if fresh_manifest:
        # capture AFTER the router exists: the manifest records the
        # resolved configs + starting fleet size actually in force
        manifest = ReplayManifest.capture(
            spec, timeline, serving_config=router.decode_config,
            router_config=router.config,
            replicas=len(router._replicas))

    auditor = InvariantAuditor(manifest=manifest.tag)
    clients = [_Client(tr) for tr in generate_trace(spec)]
    live: Dict[int, _Client] = {}         # frid -> client (bounded by
    #                                        fleet queue + slot capacity)
    retry_buckets: Dict[int, List[_Client]] = {}   # step -> fixed backoffs
    backoff: List[_Client] = []           # hint-policy wall-clock waits
    done_count = 0
    arrival_cursor = 0
    shed_submits = 0
    disconnects_pending = 0
    spawn_steps: List[int] = []
    drain_steps: List[int] = []
    autoscale_log: List[Tuple[int, str]] = []
    fleet_sizes: List[int] = []
    step = 0
    budget = max_steps if max_steps is not None else \
        spec.horizon * 40 + spec.requests * 40 + 2000
    t_start = time.time()
    cooldown_left = None

    def _adoptable_rids() -> List[int]:
        # replicas that can ADOPT failed-over work (Replica.adoptable:
        # a FULL admission queue still qualifies, resubmit bypasses the
        # queue bound) — so a kill at peak saturation is coverable
        return [rid for rid, rep in router._replicas.items()
                if rep.adoptable()]

    def _submit(cl: _Client) -> None:
        nonlocal shed_submits, done_count
        tr = cl.tr
        cl.attempts += 1
        try:
            frid = router.submit(
                tr.prompt, max_new_tokens=tr.max_new_tokens,
                eos_token_id=tr.eos_token_id, tenant=tr.tenant,
                priority=tr.priority, temperature=tr.temperature,
                top_k=tr.top_k, top_p=tr.top_p, seed=tr.seed,
                adapter_id=tr.adapter_id)
        except (ServingQueueFull, ServingUnavailable) as e:
            shed_submits += 1
            if cl.attempts >= spec.max_attempts:
                cl.state, cl.outcome = "done", "gave_up"
                done_count += 1
                return
            cl.retries += 1
            if spec.retry_policy == "hint":
                # honor the 429/503's retry_after_s against the wall
                # clock: no resubmit before the hint elapses
                ra = getattr(e, "retry_after_s", None) or 1.0
                cl.state = "backoff"
                cl.backoff_until = time.time() + float(ra)
                backoff.append(cl)
                return
            # "storm" ignores the hint (the misbehaving client the
            # backoff regression test measures against); "fixed" waits a
            # deterministic step count
            back = 1 if spec.retry_policy == "storm" \
                else spec.retry_backoff_steps
            cl.state = "waiting"
            retry_buckets.setdefault(step + back, []).append(cl)
            return
        cl.frid = frid
        cl.state = "live"
        cl.submit_step = step if cl.submit_step is None else cl.submit_step
        cl.submit_t = cl.submit_t or time.time()
        live[frid] = cl

    def _fire(ev) -> None:
        nonlocal disconnects_pending
        adoptable = _adoptable_rids()
        if ev.name == "replica_kill":
            if len(adoptable) < 2:
                timeline.log(step, ev.name, "skipped: no failover cover")
                return
            rid = max(adoptable)
            _chaos.replica_kill(router, rid=rid)
            timeline.log(step, ev.name, {"rid": rid})
        elif ev.name == "slow_replica":
            if not adoptable:
                timeline.log(step, ev.name, "skipped: none healthy")
                return
            rid = max(adoptable)
            _chaos.slow_replica(router, rid=rid, **ev.kwargs)
            timeline.log(step, ev.name, {"rid": rid, **ev.kwargs})
        elif ev.name == "flaky_probe":
            if not adoptable:
                timeline.log(step, ev.name, "skipped: none healthy")
                return
            rid = min(adoptable)
            _chaos.flaky_probe(router, rid=rid, **ev.kwargs)
            timeline.log(step, ev.name, {"rid": rid, **ev.kwargs})
        elif ev.name == "flood_tenant":
            try:
                res = _chaos.flood_tenant(
                    router, tenant="_flood", prompt_len=6,
                    max_new_tokens=2, vocab_size=spec.vocab_size,
                    eos_token_id=spec.eos_token_id, **ev.kwargs)
                timeline.log(step, ev.name,
                             {"admitted": len(res["rids"]),
                              "shed": res["shed"]})
            except ServingUnavailable:
                # "skipped" prefix: a flood that never reached the
                # admission path did not exercise this chaos kind, so
                # chaos_kinds must not count it
                timeline.log(step, ev.name, "skipped: fleet not admitting")
        elif ev.name == "poison_prompt":
            base = np.arange(1, 9, dtype=np.int32) % spec.vocab_size
            poisoned = _chaos.poison_prompt(base, spec.vocab_size,
                                            **ev.kwargs)
            try:
                frid = router.submit(poisoned, max_new_tokens=2,
                                     eos_token_id=None, tenant="_poison")
                timeline.log(step, ev.name, {"frid": frid, **ev.kwargs})
            except (ServingQueueFull, ServingUnavailable):
                # the poisoned prompt never entered an engine: skipped
                timeline.log(step, ev.name, "skipped: shed")
        elif ev.name == "host_pressure":
            if not adoptable:
                timeline.log(step, ev.name, "skipped: none healthy")
                return
            rid = min(adoptable)
            res = _chaos.host_pressure(router, rid=rid, **ev.kwargs)
            if res["enabled"]:
                timeline.log(step, ev.name, res)
            else:
                # the tier is off: the fault had nothing to squeeze
                timeline.log(step, ev.name, "skipped: offload tier off")
        elif ev.name == "corrupt_offload_block":
            # aim at a replica whose tier actually holds a block — a
            # corruption that touched nothing did not exercise the
            # checksum path and must not count as fired
            for rid in adoptable:
                res = _chaos.corrupt_offload_block(router, rid=rid,
                                                   **ev.kwargs)
                if res["enabled"] and res["key"] is not None:
                    timeline.log(step, ev.name, res)
                    return
            timeline.log(step, ev.name, "skipped: tier off or empty")
        elif ev.name == "kill_prefill_replica":
            res = _chaos.kill_prefill_replica(router, **ev.kwargs)
            if res["enabled"]:
                timeline.log(step, ev.name, res)
            else:
                # no prefill pool in this fleet: nothing to kill
                timeline.log(step, ev.name, "skipped: no prefill replica")
        elif ev.name == "stale_directory":
            res = _chaos.stale_directory(router, **ev.kwargs)
            if res["enabled"]:
                timeline.log(step, ev.name, res)
            else:
                # a poisoning that armed nothing did not exercise the
                # pull-checksum path and must not count as fired
                timeline.log(step, ev.name,
                             "skipped: directory off or empty")
        elif ev.name == "adapter_churn":
            if not adoptable:
                timeline.log(step, ev.name, "skipped: none healthy")
                return
            rid = min(adoptable)
            res = _chaos.adapter_churn(router, rid=rid, **ev.kwargs)
            if res["enabled"]:
                timeline.log(step, ev.name, res)
            else:
                # no pool / nothing registered: nothing to churn
                timeline.log(step, ev.name,
                             "skipped: multi-adapter serving off")
        elif ev.name == "disconnect_mid_stream":
            # logged when a live stream is ACTUALLY cut (or as skipped
            # at quiesce if none ever was) — an armed-but-never-fired
            # disconnect must not count as an exercised chaos kind
            disconnects_pending += 1
        else:
            raise ValueError(f"chaos timeline cannot fire {ev.name!r}")

    try:
        while True:
            for ev in timeline.due(step):
                _fire(ev)
            # arrivals due this step, fixed-backoff retries due this step,
            # hint-policy backoffs whose wall-clock hint elapsed — all O(due)
            while arrival_cursor < len(clients) and \
                    clients[arrival_cursor].tr.arrival_step <= step:
                _submit(clients[arrival_cursor])
                arrival_cursor += 1
            for cl in retry_buckets.pop(step, ()):
                if cl.state == "waiting":
                    _submit(cl)
            if backoff:
                if not live and not router.pending and not retry_buckets \
                        and arrival_cursor == len(clients):
                    # every remaining client is waiting out a wall-clock
                    # retry_after_s hint and the fleet is idle: sleep to the
                    # earliest hint instead of burning the step budget
                    # spinning empty engine steps (hint policy only — the
                    # deterministic policies never populate ``backoff``)
                    time.sleep(max(0.0,
                                   min(c.backoff_until for c in backoff)
                                   - time.time()))
                now = time.time()
                due = [cl for cl in backoff if now >= cl.backoff_until]
                if due:
                    backoff[:] = [cl for cl in backoff
                                  if now < cl.backoff_until]
                    for cl in due:
                        cl.state, cl.backoff_until = "waiting", None
                        _submit(cl)
            emitted = router.step(spec.step_iters)
            auditor.observe(emitted, lookup=router._reqs.get)
            now = time.time()
            for frid, toks in emitted.items():
                cl = live.get(frid)
                if cl is None:
                    continue                       # flood/poison side traffic
                if cl.first_step is None and toks:
                    cl.first_step, cl.first_t = step, now
                if not (cl.behavior_fired and cl.tr.behavior == "abandon"):
                    cl.delivered.extend(int(t) for t in toks)
            # client misbehavior + deadlines + armed disconnects — O(live)
            for frid, cl in list(live.items()):
                tr = cl.tr
                if tr.behavior != "normal" and not cl.behavior_fired and \
                        len(cl.delivered) >= tr.behavior_at:
                    cl.behavior_fired = True
                    if tr.behavior in ("cancel", "disconnect"):
                        router.cancel(frid)
                    # abandon: the client stops READING; the stream runs on
                    # and the driver cancels it a few steps later — the GC of
                    # an abandoned iterator, made deterministic
                if tr.behavior == "abandon" and cl.behavior_fired and \
                        cl.first_step is not None and \
                        step - cl.first_step >= tr.behavior_at + 3:
                    router.cancel(frid)
                if tr.deadline_steps is not None and \
                        cl.submit_step is not None and \
                        step - cl.submit_step > tr.deadline_steps:
                    rec = router._reqs.get(frid)
                    if rec is not None and not rec.terminal:
                        router.cancel(frid)
                        cl.outcome = "deadline"
                if disconnects_pending and tr.behavior == "normal" \
                        and cl.delivered and cl.outcome is None:
                    rec = router._reqs.get(frid)
                    if rec is not None and not rec.terminal:
                        disconnects_pending -= 1
                        router.cancel(frid)
                        cl.outcome = "disconnected"
                        timeline.log(step, "disconnect_mid_stream",
                                     {"frid": frid})
            # terminal sweep (authoritative tokens/state from the router)
            for frid, cl in list(live.items()):
                rec = router._reqs.get(frid)
                if rec is None or not rec.terminal:
                    continue
                auditor.close_request(frid, rec)
                del live[frid]
                cl.state = "done"
                done_count += 1
                cl.finish_step, cl.finish_t = step, time.time()
                cl.delivered = [int(t) for t in rec.tokens]
                if rec.state == FAILED:
                    cl.outcome = "failed"
                elif rec.state == FINISHED:
                    cl.outcome = cl.outcome or "finished"
                else:
                    cl.outcome = cl.outcome or "cancelled"
            if spec.autoscale_every and step \
                    and step % spec.autoscale_every == 0:
                sig = router.autoscale()
                autoscale_log.append((step, sig["action"]))
                if "spawned" in sig:
                    spawn_steps.append(step)
                if "retiring" in sig:
                    drain_steps.append(step)
            if spec.audit_every and step and step % spec.audit_every == 0:
                auditor.check(router, collect=collect_violations)
            fleet_sizes.append(len(router._replicas))
            step += 1
            if step > budget:
                raise RuntimeError(
                    f"replay exceeded its step budget ({budget}); "
                    f"{len(clients) - done_count} client(s) unfinished "
                    f"[{manifest.tag}]")
            done = arrival_cursor == len(clients) \
                and done_count == len(clients) and not backoff \
                and not router.pending
            if done and cooldown_left is None:
                cooldown_left = spec.cooldown_steps
            if cooldown_left is not None:
                cooldown_left -= 1
                # a chaos event firing inside the cooldown window (flood /
                # poison side traffic) re-opens work: keep stepping until the
                # fleet genuinely drains, so quiesce audits an idle fleet
                if cooldown_left <= 0 and not router.pending \
                        and not timeline.remaining:
                    break

        auditor.quiesce(router, collect=collect_violations)
        if disconnects_pending:
            # armed disconnects that never found an eligible live
            # stream: recorded as skipped so chaos_kinds stays honest
            timeline.log(step, "disconnect_mid_stream",
                         f"skipped: {disconnects_pending} armed, no "
                         f"eligible stream")
    except BaseException:
        # a raising replay (InvariantViolation, step-budget overrun,
        # KeyboardInterrupt) must not strand the fleet it built —
        # close frees every replica's KV pool and supervisor state
        if own_router:
            try:
                router.close(0)
            except Exception:
                pass
        raise
    elapsed = time.time() - t_start

    # ---- metrics ----------------------------------------------------------
    finished = [c for c in clients if c.outcome == "finished"]
    ttft_steps = [c.first_step - c.submit_step for c in clients
                  if c.first_step is not None and c.submit_step is not None]
    # arrival -> first token: the latency the CLIENT feels — includes
    # every shed-and-retry wait, which submit-based TTFT hides (a fleet
    # that sheds half its arrivals shows a flattering submit-TTFT while
    # clients burn retry rounds). The autoscale-effect comparison reads
    # THIS curve.
    arrival_ttft = [c.first_step - c.tr.arrival_step for c in clients
                    if c.first_step is not None]
    ttft_s = [c.first_t - c.submit_t for c in clients
              if c.first_t and c.submit_t]
    tpot_s = [(c.finish_t - c.first_t) / (len(c.delivered) - 1)
              for c in finished
              if c.finish_t and c.first_t and len(c.delivered) > 1]
    first_spawn = spawn_steps[0] if spawn_steps else None
    pre = [c.first_step - c.submit_step for c in clients
           if c.first_step is not None and c.submit_step is not None
           and (first_spawn is None or c.submit_step < first_spawn)]
    post = [c.first_step - c.submit_step for c in clients
            if c.first_step is not None and c.submit_step is not None
            and first_spawn is not None and c.submit_step >= first_spawn]
    # the autoscale-effect windows: requests submitted INTO the
    # saturation that triggered the first spawn vs requests submitted
    # after the spawned capacity had time to absorb the queue — both
    # STEP-indexed, so the comparison is deterministic per manifest and
    # host-load-immune (the p99-effect assert the bench row closes the
    # signal -> spawn -> measured-effect loop with)
    w = spec.autoscale_every
    at_spawn = [c.first_step - c.submit_step for c in clients
                if c.first_step is not None and c.submit_step is not None
                and first_spawn is not None
                and first_spawn - w <= c.submit_step < first_spawn]
    after_spawn = [c.first_step - c.submit_step for c in clients
                   if c.first_step is not None
                   and c.submit_step is not None
                   and first_spawn is not None
                   and c.submit_step >= first_spawn + w]
    good = [c for c in finished
            if c.tr.deadline_steps is None
            or (c.finish_step - c.submit_step) <= c.tr.deadline_steps]
    good_tokens = sum(len(c.delivered) for c in good)
    mean_fleet = float(np.mean(fleet_sizes)) if fleet_sizes else 1.0
    chips = max(1e-9, mean_fleet * tp)
    goodput = good_tokens / max(elapsed, 1e-9)
    outcomes: Dict[str, int] = {}
    for c in clients:
        outcomes[c.outcome or c.state] = \
            outcomes.get(c.outcome or c.state, 0) + 1
    prompt_lens = [len(c.tr.prompt) for c in clients]
    mean_seq = float(np.mean([len(c.tr.prompt) + c.tr.max_new_tokens
                              for c in clients]))

    report: Dict[str, Any] = {
        "manifest": manifest,
        "manifest_json": manifest.to_json(),
        "requests": len(clients),
        "outcomes": outcomes,
        "completed": len(finished),
        "failed": outcomes.get("failed", 0),
        "gave_up": outcomes.get("gave_up", 0),
        "retries": sum(c.retries for c in clients),
        "shed_submits": shed_submits,
        "steps": step,
        "elapsed_s": round(elapsed, 3),
        "req_s": round(len(finished) / max(elapsed, 1e-9), 2),
        "tokens_delivered": sum(len(c.delivered) for c in clients),
        "good_tokens": good_tokens,
        "goodput_tok_s": round(goodput, 2),
        "goodput_tok_s_per_chip": round(goodput / chips, 2),
        "chips": round(chips, 2),
        "mean_fleet": round(mean_fleet, 2),
        "tp": tp,
        "ttft_steps_p50": _pct(ttft_steps, 50),
        "ttft_steps_p99": _pct(ttft_steps, 99),
        "arrival_ttft_steps_p50": _pct(arrival_ttft, 50),
        "arrival_ttft_steps_p99": _pct(arrival_ttft, 99),
        "ttft_s_p50": _pct(ttft_s, 50),
        "ttft_s_p99": _pct(ttft_s, 99),
        "tpot_s_p50": _pct(tpot_s, 50),
        "tpot_s_p99": _pct(tpot_s, 99),
        "pre_spawn_ttft_p99_steps": _pct(pre, 99),
        "post_spawn_ttft_p99_steps": _pct(post, 99),
        "ttft_p99_at_spawn_steps": _pct(at_spawn, 99),
        "ttft_p99_after_spawn_steps": _pct(after_spawn, 99),
        "autoscale": {"spawns": len(spawn_steps),
                      "drains": len(drain_steps),
                      "spawn_steps": spawn_steps,
                      "drain_steps": drain_steps,
                      "log": autoscale_log},
        "chaos_fired": list(timeline.fired),
        "chaos_kinds": sorted({name for _, name, d in timeline.fired
                               if not (isinstance(d, str)
                                       and d.startswith("skipped"))}),
        # the FULL accumulated set (collecting mode retains what the
        # sampled mid-replay audits found too, not just the quiesce
        # pass — a transient violation that self-healed still fails
        # the run)
        "violations": [str(v) for v in auditor.violations],
        "audit": auditor.digest(),
        "audit_trail": list(auditor.trail),
        "router_failed": int(router.failed),
        "adapter_requests": sum(1 for c in clients
                                if c.tr.adapter_id is not None),
        "adapter_affinity_hits": int(router.adapter_affinity_hits),
        "adapter_loads": int(router.adapter_loads),
        "leaked_blocks": sum(p["in_use"] for p in
                             router.block_partitions().values()),
        "prompt_len_mean": round(float(np.mean(prompt_lens)), 2),
    }
    if record_streams:
        report["streams"] = {c.tr.tid: list(c.delivered) for c in clients}
    report["capacity"] = capacity_report(
        model_config, router.decode_config, measured=report,
        mean_seq_tokens=mean_seq, hbm_gb=hbm_gb, host_gb=host_gb)
    if own_router:
        drain = router.close(0)
        report["drain_report"] = drain
    return report


def capacity_report(model_config, serving_config, measured: Optional[Dict]
                    = None, mean_seq_tokens: Optional[float] = None,
                    hbm_gb: float = 16.0, host_gb: float = 0.0,
                    tp_degrees: Sequence[int] = (1, 2, 4, 8)
                    ) -> Dict[str, Any]:
    """The capacity-planning arithmetic + the measured curves in one
    record: per-block bytes across fp/int8 x TP degree
    (:func:`~paddle_tpu.models.generation.paged_pool_block_bytes`), the
    concurrent sequences one chip's HBM budget backs at the trace's mean
    sequence length, the EFFECTIVE cached tokens once the host-RAM
    offload tier extends the prefix cache past HBM (ISSUE 16 —
    ``host_gb`` sizes the tier; 0 falls back to the configured
    ``offload_blocks`` bound when the tier is on, since an int8 host
    block is ~3.5x cheaper the same host budget holds ~3.5x the cached
    tokens), and — when a replay's ``measured`` record is given — the 'X
    replicas of config Y serve Z req/s within SLO' sizing line the
    report exists for."""
    from ...models.generation import paged_pool_block_bytes, validate_tp
    bs = int(serving_config.block_size)
    hbm = int(hbm_gb * (1 << 30))
    host = int(host_gb * (1 << 30))
    tier_on = bool(getattr(serving_config, "offload", False))
    tier_blocks = int(getattr(serving_config, "offload_blocks", 0) or 0) \
        if tier_on else 0
    seq = float(mean_seq_tokens
                if mean_seq_tokens is not None
                else serving_config.max_model_len)
    blocks_per_seq = max(1, math.ceil(seq / bs))
    layouts: Dict[str, Dict[str, Any]] = {}
    for kv in (None, "int8"):
        for tp in tp_degrees:
            try:
                validate_tp(model_config, tp)
            except ValueError:
                continue
            bb = paged_pool_block_bytes(model_config, bs, kv_quant=kv,
                                        tp=tp)
            blocks = hbm // bb
            # host-tier column: an explicit host budget wins; otherwise
            # the configured tier bound (0 rows when the tier is off)
            host_blocks = (host // bb) if host else tier_blocks
            layouts[f"{kv or 'fp'}_tp{tp}"] = {
                "block_bytes_per_chip": int(bb),
                "blocks_per_chip": int(blocks),
                "concurrent_seqs_per_chip": int(blocks // blocks_per_seq),
                "host_blocks_per_chip": int(host_blocks),
                "cached_tokens_hbm": int(blocks * bs),
                "cached_tokens_hbm_plus_host": int(
                    (blocks + host_blocks) * bs),
            }
    report: Dict[str, Any] = {
        "config": {
            "layers": model_config.num_hidden_layers,
            "kv_heads": model_config.kv_heads,
            "head_dim": model_config.head_dim,
            "block_size": bs,
            "kv_quant": serving_config.kv_quant,
            "tp": serving_config.tp,
            "max_slots": serving_config.max_slots,
            "offload": tier_on,
            "offload_blocks": tier_blocks,
        },
        "hbm_budget_bytes_per_chip": hbm,
        "host_budget_bytes_per_chip": host,
        "mean_seq_tokens": round(seq, 1),
        "blocks_per_seq": blocks_per_seq,
        "layouts": layouts,
    }
    if measured:
        per_replica_req_s = measured["req_s"] / max(
            measured.get("mean_fleet", 1.0), 1e-9)
        report["measured"] = {
            "req_s": measured["req_s"],
            "req_s_per_replica": round(per_replica_req_s, 3),
            "goodput_tok_s_per_chip": measured["goodput_tok_s_per_chip"],
            "ttft_s_p50": measured["ttft_s_p50"],
            "ttft_s_p99": measured["ttft_s_p99"],
            "tpot_s_p50": measured["tpot_s_p50"],
            "tpot_s_p99": measured["tpot_s_p99"],
            "mean_fleet": measured.get("mean_fleet"),
        }
        for target in (10, 100, 1000):
            report["measured"][f"replicas_for_{target}_req_s"] = \
                int(math.ceil(target / max(per_replica_req_s, 1e-9)))
        report["sizing"] = (
            f"{measured.get('mean_fleet')} replica(s) of "
            f"{model_config.num_hidden_layers}L/"
            f"{model_config.kv_heads}kvh/bs{bs}"
            f"{'/' + serving_config.kv_quant if serving_config.kv_quant else ''}"
            f"/tp{serving_config.tp} served "
            f"{measured['req_s']} req/s within SLO "
            f"(p99 TTFT {measured['ttft_s_p99']}s, "
            f"goodput {measured['goodput_tok_s_per_chip']} tok/s/chip)")
    return report
