"""``paddle.io`` parity: datasets, samplers, DataLoader.

Reference surface: ``python/paddle/io/__init__.py``.
"""

from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)
from .dataloader import (DataLoader, WorkerInfo, default_collate_fn,
                         default_convert_fn, get_worker_info,
                         prefetch_to_device)

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "SubsetRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "WorkerInfo", "get_worker_info", "default_collate_fn",
    "default_convert_fn", "prefetch_to_device",
]
