"""DataLoader: multiprocess workers + host->device prefetch.

Parity target: ``python/paddle/io/dataloader/`` in the reference (DataLoader
with worker subprocesses, shared-memory tensor transport, buffered reader,
IterableDataset worker splitting). TPU redesign (SURVEY §7 hard-part 6 —
keep the MXUs fed):

* workers are ``fork`` subprocesses that ONLY touch numpy (they must never
  initialize the PJRT client); batches cross process boundaries as pickled
  numpy arrays and are wrapped to Tensors in the parent,
* ``use_buffer_reader=True`` adds a host->device double-buffer: the next
  ``prefetch_factor`` batches are ``jax.device_put`` issued ahead of use, so
  the async dispatch overlaps the device step (the TPU analogue of the
  reference's pin-memory + CUDA-stream copy pipeline).
"""

from __future__ import annotations

import collections
import itertools
import multiprocessing as mp
import queue as pyqueue
import signal as _signal
import time
import traceback
import warnings
from typing import Any, Callable, List, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "get_worker_info", "default_collate_fn",
           "default_convert_fn", "WorkerInfo", "prefetch_to_device"]


def _describe_exit(code: Optional[int]) -> str:
    """Human-readable worker exit: decodes the signal for negative codes
    (multiprocessing convention) so 'exit code -9' reads as the OOM kill
    it almost always is."""
    if code is None:
        return "still exiting"
    if code < 0:
        try:
            name = _signal.Signals(-code).name
        except ValueError:
            name = f"signal {-code}"
        hint = " (likely the kernel OOM killer)" if -code == 9 else \
            " (segfault in dataset/native code)" if -code == 11 else ""
        return f"killed by {name}{hint}"
    return f"exit code {code}"


def _fetch_sample(dataset, idx, retries: int, backoff_s: float):
    """``dataset[idx]`` with bounded retry + exponential backoff — the
    self-healing path for transient failures (flaky remote reads, racing
    decoders). Deterministic failures exhaust the retries and re-raise
    for the caller's quarantine/raise decision."""
    attempt = 0
    while True:
        try:
            return dataset[idx]
        except Exception:
            if attempt >= retries:
                raise
            time.sleep(backoff_s * (2 ** attempt))
            attempt += 1


class _SkippedBatch:
    """Worker->parent marker: every index of this batch is quarantined —
    the batch is dropped, the epoch continues."""


def _gather_batch(dataset, indices, quarantined: set, retries: int,
                  backoff_s: float, quarantine: bool, who: str = "DataLoader",
                  on_quarantine: Optional[Callable] = None):
    """Fetch a batch's samples with the self-healing policy — shared by
    the worker loop and the single-process path so the retry/quarantine
    semantics cannot drift apart. Mutates ``quarantined`` in place; calls
    ``on_quarantine(idx)`` for each NEWLY quarantined index.

    Returns the item list, or ``None`` when quarantine healing left the
    batch EMPTY (every index bad) — the batch is skipped, not fatal: a
    self-healing loader must survive even a fully-poisoned batch."""
    items, last_exc = [], None
    for i in indices:
        if i in quarantined:
            continue
        try:
            items.append(_fetch_sample(dataset, i, retries, backoff_s))
        except Exception as e:
            if not quarantine:
                raise
            # self-healing: drop the sample, remember the index so it is
            # never re-fetched (and never re-pays the retries)
            last_exc = e
            quarantined.add(i)
            if on_quarantine is not None:
                on_quarantine(i)
            warnings.warn(
                f"{who}: sample {i} failed {retries + 1}x and was "
                f"quarantined ({type(e).__name__}: {e}); the batch "
                f"continues without it")
    if not items:
        if quarantine:
            if last_exc is not None:   # newly emptied this epoch: say so
                warnings.warn(f"{who}: every index of a batch is "
                              f"quarantined; skipping the batch")
            return None
        raise last_exc if last_exc is not None else RuntimeError(
            "batch: every index quarantined")
    return items


class WorkerInfo:
    def __init__(self, id: int, num_workers: int, seed: int, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker: this worker's (id, num_workers, seed, dataset);
    ``None`` in the main process (reference parity)."""
    return _worker_info


def default_convert_fn(batch):
    return batch


def default_collate_fn(batch: List[Any]):
    """Stack a list of samples into batched numpy arrays (nested structures
    follow the reference: dict -> dict of stacks, tuple -> tuple of stacks)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (np.floating, float)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (np.integer, int)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn(list(fields))
                            for fields in zip(*batch))
    # Tensor / jax array / anything array-like
    try:
        return np.stack([np.asarray(s) for s in batch])
    except Exception:
        return batch


class _ExceptionWrapper:
    def __init__(self, exc):
        self.exc_type = type(exc).__name__
        self.msg = f"{exc}\n{traceback.format_exc()}"

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.exc_type}: {self.msg}")


_RING_FALLBACK_WARNED = False


class _RingSource:
    """Round-robin poll of per-worker shm rings behind a Queue-like .get.
    ``rings`` is mutated in place by worker resurrection (a replacement
    worker gets a FRESH ring — the dead worker may have died mid-push,
    leaving its old ring's slot state unusable)."""

    def __init__(self, rings):
        self.rings = list(rings)
        self._next = 0

    def swap(self, idx, new_ring):
        old = self.rings[idx]
        self.rings[idx] = new_ring
        try:
            old.close()
        except Exception:
            pass

    def get(self, timeout=None):
        import pickle
        import time
        deadline = None if timeout is None else time.time() + timeout
        while True:
            for _ in range(len(self.rings)):
                r = self.rings[self._next]
                self._next = (self._next + 1) % len(self.rings)
                data = r.pop(timeout_ms=2)
                if data is not None:
                    return pickle.loads(data)
            if deadline is not None and time.time() > deadline:
                raise pyqueue.Empty


def _worker_loop(dataset, index_queue, result_queue, collate_fn, init_fn,
                 worker_id, num_workers, seed, iterable, ring=None,
                 all_rings=(), retry_cfg=(0, 0.05, False, frozenset())):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, seed, dataset)
    np.random.seed(seed % (2 ** 31))
    # forked children inherit owner=True ring handles; they must not destroy
    # the parent's semaphores / shm at interpreter exit (ADVICE r2)
    for r in all_rings:
        try:
            r.disown()
        except Exception:
            pass
    if ring is not None:
        import pickle

        class _RingPut:
            def put(self, item):
                try:
                    ring.push(pickle.dumps(item,
                                           protocol=pickle.HIGHEST_PROTOCOL))
                except ValueError as e:  # payload exceeds slot capacity
                    ring.push(pickle.dumps((item[0], _ExceptionWrapper(e))))
        result_queue = _RingPut()
    try:
        if init_fn is not None:
            init_fn(worker_id)
    except Exception as e:  # init failure poisons every batch
        result_queue.put((-1, _ExceptionWrapper(e)))
        return
    if iterable:
        # stream split: worker w takes items w, w+N, w+2N, ... and batches
        # arrive pre-chunked as (batch_idx, batch_size) requests
        it = itertools.islice(iter(dataset), worker_id, None, num_workers)
        while True:
            req = index_queue.get()
            if req is None:
                return
            bidx, bsize = req
            items = list(itertools.islice(it, bsize))
            if not items:
                result_queue.put((bidx, StopIteration()))
                continue
            try:
                result_queue.put((bidx, collate_fn(items)))
            except Exception as e:
                result_queue.put((bidx, _ExceptionWrapper(e)))
    else:
        retries, backoff_s, quarantine, initial_q = retry_cfg
        # seeded from the parent loader's set at fork: indices quarantined
        # in earlier epochs (reported back via the (-2, idx) notice) are
        # skipped immediately instead of re-paying the retries
        quarantined: set = set(initial_q)
        while True:
            req = index_queue.get()
            if req is None:
                return
            bidx, indices = req
            try:
                items = _gather_batch(
                    dataset, indices, quarantined, retries, backoff_s,
                    quarantine, who=f"DataLoader worker {worker_id}",
                    # tell the parent so the NEXT epoch's workers inherit
                    on_quarantine=lambda i: result_queue.put((-2, i)))
                result_queue.put((bidx, _SkippedBatch() if items is None
                                  else collate_fn(items)))
            except Exception as e:
                result_queue.put((bidx, _ExceptionWrapper(e)))


def _to_tensors(batch, device=None):
    """numpy batch -> Tensor pytree (device transfer happens here; under the
    buffered reader several of these are in flight ahead of consumption)."""
    from ..core.tensor import Tensor, to_tensor
    if isinstance(batch, np.ndarray):
        return to_tensor(batch, place=device)
    if isinstance(batch, dict):
        return {k: _to_tensors(v, device) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(_to_tensors(v, device) for v in batch)
    return batch


def prefetch_to_device(iterable, size: int = 2, device=None):
    """Double-buffered host->device prefetch iterator (the TPU analogue of
    the reference's pin-memory + CUDA-stream copy pipeline, as a standalone
    generator usable over ANY batch iterable, not just DataLoader).

    Keeps ``size`` batches' transfers in flight ahead of the consumer:
    ``jax.device_put`` dispatch is async, so while the device runs step N
    the host is collating batch N+1 ("data" span) and its H2D transfer
    ("h2d" span) streams concurrently — the input pipeline disappears from
    the step time once ``host+h2d < step``. Spans are emitted when
    ``FLAGS_profile_annotations`` is on.

    CPU degradation: there is no host/device overlap to win and "transfers"
    are memcpys, so the buffer collapses to a plain convert-and-yield loop
    (single-buffer fallback) — no extra batch latency in tier-1 tests.

    Batches may be numpy arrays, Tensors, or nested dict/tuple/list pytrees
    of them; ``device`` is an optional Place to pin transfers to.
    """
    from ..profiler import annotate

    it = iter(iterable)
    if not donation_like_backend_supports_overlap():
        for b in it:
            yield _to_tensors(b, device)
        return
    size = max(1, int(size))
    buf = collections.deque()

    def _fill():
        with annotate("data"):
            try:
                b = next(it)
            except StopIteration:
                return False
        with annotate("h2d"):
            buf.append(_to_tensors(b, device))
        return True

    while len(buf) < size and _fill():
        pass
    while buf:
        out = buf.popleft()
        # issue the next transfer BEFORE handing the current batch out, so
        # the H2D copy overlaps the consumer's device step
        _fill()
        yield out


def donation_like_backend_supports_overlap() -> bool:
    """Async-dispatch H2D overlap exists off-CPU (same backend split as
    jit.train_step.donation_supported; kept separate so io never imports
    jit)."""
    import jax
    return jax.default_backend() not in ("cpu",)


class _WorkerSet:
    """Worker processes + transport + in-flight bookkeeping, with
    resurrection: a dead worker (OOM kill, segfault in dataset code) is
    replaced by a fresh fork — same worker id, FRESH index queue and shm
    ring (the old ones may hold a torn request/push from the death) — and
    every batch that was in flight on it is re-queued, so one lost worker
    costs a recompute instead of the epoch.

    Resurrection is map-style only: an IterableDataset worker's stream
    position died with the process, so replaying its requests would
    silently skip or duplicate samples — those keep the fail-fast path.
    """

    def __init__(self, loader: "DataLoader"):
        self.loader = loader
        self.ctx = mp.get_context("fork")  # workers reuse the parent dataset
        self.nw = loader.num_workers
        self.result_queue = self.ctx.Queue()
        self.rings = loader._make_rings(self.nw)
        self.result_src = (_RingSource(self.rings) if self.rings
                           else self.result_queue)
        self.base_seed = np.random.randint(0, 2 ** 31 - 1)
        self.index_queues: List = []
        self.procs: List = []
        self.inflight: dict = {}       # bidx -> (worker_id, payload)
        self.restarts_left = (0 if loader._iterable
                              else loader.worker_restarts)
        self.generation = 0
        for w in range(self.nw):
            self.index_queues.append(self.ctx.Queue())
            self.procs.append(self._spawn(w))

    def _spawn(self, w: int):
        ring = self.rings[w] if self.rings else None
        p = self.ctx.Process(
            target=_worker_loop,
            args=(self.loader.dataset, self.index_queues[w],
                  self.result_queue, self.loader.collate_fn,
                  self.loader.worker_init_fn, w, self.nw,
                  self.base_seed + w + self.generation * self.nw,
                  self.loader._iterable, ring,
                  tuple(self.rings) if self.rings else (),
                  (self.loader.sample_retries,
                   self.loader.sample_retry_backoff,
                   self.loader.quarantine_bad_samples,
                   frozenset(self.loader._quarantined))),
            daemon=True)
        p.start()
        return p

    # -- in-flight bookkeeping (map-style) ----------------------------------
    def submit(self, bidx: int, payload):
        w = bidx % self.nw
        self.index_queues[w].put((bidx, payload))
        self.inflight[bidx] = (w, payload)

    def done(self, bidx: int):
        self.inflight.pop(bidx, None)

    def revive(self, dead) -> bool:
        """Replace dead workers and re-queue their in-flight batches.
        Returns False (caller raises) when the restart budget is spent or
        the dataset is iterable."""
        if self.restarts_left < len(dead):
            return False
        self.restarts_left -= len(dead)
        self.generation += 1
        for w, code in dead:
            warnings.warn(
                f"DataLoader worker {w} died ({_describe_exit(code)}); "
                f"resurrecting it and re-queuing "
                f"{sum(1 for ww, _ in self.inflight.values() if ww == w)} "
                f"in-flight batch(es) "
                f"({self.restarts_left} restart(s) left)")
            try:
                self.procs[w].join(timeout=0.1)
            except Exception:
                pass
            # fresh queue + ring: the old ones may be torn mid-operation
            self.index_queues[w] = self.ctx.Queue()
            if self.rings:
                try:
                    new_ring = self.loader._make_ring(w, self.generation)
                except Exception:
                    return False     # can't rebuild transport — fail fast
                self.rings[w] = new_ring
                self.result_src.swap(w, new_ring)
            self.procs[w] = self._spawn(w)
            for bidx, (ww, payload) in sorted(self.inflight.items()):
                if ww == w:
                    self.index_queues[w].put((bidx, payload))
        return True

    def shutdown(self):
        for iq in self.index_queues:
            try:
                iq.put(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=1.0)
            if p.is_alive():
                p.terminate()
        if self.rings:
            for r in self.rings:
                try:
                    r.close()
                except Exception:
                    pass


class DataLoader:
    """ref: paddle.io.DataLoader (return_list=True semantics only — the
    legacy feed-dict mode targets the static graph executor, which this
    framework replaces with jit; pass ``feed_list`` for API compat, it is
    ignored)."""

    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler=None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable] = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: float = 0, worker_init_fn: Optional[Callable] = None,
                 persistent_workers: bool = False,
                 sample_retries: Optional[int] = None,
                 sample_retry_backoff: Optional[float] = None,
                 quarantine_bad_samples: Optional[bool] = None,
                 worker_restarts: Optional[int] = None):
        """Self-healing knobs (docs/FAULT_TOLERANCE.md "Runtime anomalies";
        defaults come from the FLAGS_health_* flags, which default OFF so
        error propagation is unchanged unless opted in):

        * ``sample_retries`` — retry a failing ``Dataset.__getitem__``
          with bounded exponential backoff (transient I/O);
        * ``quarantine_bad_samples`` — after the retries, drop the sample
          and quarantine its index (warn once) instead of poisoning the
          epoch (defaults on when retries are enabled);
        * ``worker_restarts`` — resurrect a dead worker process
          (OOM-kill, segfault) up to N times, re-queuing its in-flight
          batches (map-style datasets; an iterable worker's stream
          position died with it, so those still fail fast).
        """
        from ..flags import flag
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = bool(use_shared_memory)
        self.shm_slot_bytes = 32 << 20
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.sample_retries = int(
            flag("FLAGS_health_data_retries") if sample_retries is None
            else sample_retries)
        self.sample_retry_backoff = float(
            flag("FLAGS_health_data_backoff_s")
            if sample_retry_backoff is None else sample_retry_backoff)
        self.quarantine_bad_samples = bool(
            self.sample_retries > 0 if quarantine_bad_samples is None
            else quarantine_bad_samples)
        self.worker_restarts = int(
            flag("FLAGS_health_worker_restarts") if worker_restarts is None
            else worker_restarts)
        self._quarantined: set = set()   # num_workers=0 path
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            if batch_sampler is not None or shuffle:
                raise ValueError(
                    "IterableDataset does not accept batch_sampler/shuffle")
            self.batch_size = int(batch_size)
            self.drop_last = bool(drop_last)
            self.batch_sampler = None
        elif batch_sampler is not None:
            if batch_size != 1 or shuffle or drop_last:
                raise ValueError(
                    "batch_sampler is mutually exclusive with "
                    "batch_size/shuffle/drop_last")
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self.batch_size = int(batch_size)

    def __len__(self):
        if self._iterable:
            raise TypeError("DataLoader over an IterableDataset has no length")
        return len(self.batch_sampler)

    # -- iteration ----------------------------------------------------------

    def _raw_batches(self):
        """Yield collated numpy batches (single- or multi-process)."""
        if self.num_workers == 0:
            if self._iterable:
                it = iter(self.dataset)
                while True:
                    items = list(itertools.islice(it, self.batch_size))
                    if not items or (self.drop_last and
                                     len(items) < self.batch_size):
                        return
                    yield self.collate_fn(items)
            else:
                for indices in self.batch_sampler:
                    items = self._fetch_batch(indices)
                    if items is None:   # fully-quarantined batch: skip
                        continue
                    yield self.collate_fn(items)
            return
        yield from self._multiprocess_batches()

    def _fetch_batch(self, indices):
        """Single-process fetch with the same retry/quarantine healing the
        workers apply (shared quarantine set across epochs)."""
        return _gather_batch(self.dataset, indices, self._quarantined,
                             self.sample_retries, self.sample_retry_backoff,
                             self.quarantine_bad_samples)

    def _make_rings(self, nw):
        """Shared-memory transport (native C++ ring; reference shm parity).
        Falls back to mp.Queue when the native lib is unavailable — with
        ONE warning saying why, instead of silently downgrading every
        loader in the process to the slow path."""
        if not self.use_shared_memory:
            return None
        try:
            return [self._make_ring(w) for w in range(nw)]
        except Exception as e:
            global _RING_FALLBACK_WARNED
            if not _RING_FALLBACK_WARNED:
                _RING_FALLBACK_WARNED = True
                warnings.warn(
                    f"DataLoader: shared-memory ring transport unavailable "
                    f"({type(e).__name__}: {e}); falling back to the slower "
                    f"mp.Queue transport (pass use_shared_memory=False to "
                    f"silence)")
            return None

    def _make_ring(self, w: int, generation: int = 0):
        import os
        from ..native import ShmRing
        tag = f"/pt_dl_{os.getpid()}_{id(self) & 0xffffff}"
        suffix = f"_r{generation}" if generation else ""
        return ShmRing(f"{tag}_{w}{suffix}", slots=4,
                       slot_bytes=self.shm_slot_bytes)

    def _multiprocess_batches(self):
        ws = _WorkerSet(self)
        try:
            if self._iterable:
                yield from self._mp_iterable(ws.index_queues, ws.result_src,
                                             ws.nw, ws.procs)
            else:
                yield from self._mp_map(ws)
        finally:
            ws.shutdown()

    def _get(self, result_queue, workers=(), revive=None):
        """Queue get with a liveness watchdog: wait in short slices; when a
        worker died (OOM-kill/segfault) either resurrect it via ``revive``
        (self-healing map-style path) or fail fast with the worker's
        decoded exit signal instead of blocking forever."""
        from ..health import watchdog
        deadline = (None if not self.timeout
                    else time.monotonic() + self.timeout)
        while True:
            slice_t = 1.0
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s waiting "
                        f"for a worker batch")
                slice_t = min(slice_t, left)
            try:
                out = result_queue.get(timeout=slice_t)
                # progress tick ONLY on a real batch: ticking the empty
                # poll slices would mask exactly the stalled-input hang
                # the watchdog exists to catch
                watchdog.touch()
                return out
            except pyqueue.Empty:
                dead = [(i, p.exitcode) for i, p in enumerate(workers)
                        if not p.is_alive()]
                if dead:
                    # final drain: a worker may have enqueued its result (or
                    # the real exception) just before exiting — surface that
                    # instead of a misleading died-unexpectedly error
                    try:
                        return result_queue.get(timeout=0.2)
                    except pyqueue.Empty:
                        pass
                    if revive is not None and revive(dead):
                        continue   # replacements spawned, work re-queued
                    descr = ", ".join(
                        f"worker {i}: {_describe_exit(c)}" for i, c in dead)
                    raise RuntimeError(
                        f"DataLoader worker(s) died unexpectedly ({descr}); "
                        f"the remaining batch will never arrive. Map-style "
                        f"datasets can self-heal via worker_restarts= / "
                        f"FLAGS_health_worker_restarts."
                    ) from None

    def _mp_map(self, ws: "_WorkerSet"):
        batches = list(self.batch_sampler)
        depth = min(len(batches), self.prefetch_factor * ws.nw)
        for nxt in range(depth):
            ws.submit(nxt, batches[nxt])
        nxt = depth
        reorder = {}
        for want in range(len(batches)):
            while want not in reorder:
                bidx, data = self._get(ws.result_src, ws.procs,
                                       revive=ws.revive)
                if bidx == -2:
                    # quarantine notice: the next epoch's workers (a fresh
                    # fork) inherit it and skip the index outright
                    self._quarantined.add(data)
                    continue
                if bidx == -1 or isinstance(data, _ExceptionWrapper):
                    if isinstance(data, _ExceptionWrapper):
                        data.reraise()
                ws.done(bidx)
                reorder[bidx] = data
            data = reorder.pop(want)
            if nxt < len(batches):
                ws.submit(nxt, batches[nxt])
                nxt += 1
            if isinstance(data, _SkippedBatch):
                continue            # fully-quarantined batch: dropped
            yield data

    def _mp_iterable(self, index_queues, result_queue, nw, workers=()):
        # request batches round-robin; a worker answering StopIteration is
        # retired, remaining workers drain their stream tails
        active = set(range(nw))
        bidx = 0
        inflight = collections.deque()
        depth = self.prefetch_factor * nw

        def request():
            nonlocal bidx
            if not active:
                return False
            w = bidx % nw
            if w not in active:
                w = next(iter(active))
            index_queues[w].put((bidx, self.batch_size))
            inflight.append(bidx)
            bidx += 1
            return True

        for _ in range(depth):
            request()
        reorder = {}
        want = 0
        done = set()
        while inflight:
            while inflight[0] not in reorder:
                i, data = self._get(result_queue, workers)
                if isinstance(data, _ExceptionWrapper):
                    data.reraise()
                reorder[i] = data
            i = inflight.popleft()
            data = reorder.pop(i)
            if isinstance(data, StopIteration):
                done.add(i)
                active.discard(i % nw)
                continue
            if len(data if isinstance(data, list) else [0]) and request():
                pass
            if self.drop_last and self._batch_len(data) < self.batch_size:
                continue
            yield data

    @staticmethod
    def _batch_len(data):
        if isinstance(data, np.ndarray):
            return data.shape[0]
        if isinstance(data, dict):
            return DataLoader._batch_len(next(iter(data.values())))
        if isinstance(data, (tuple, list)) and data:
            return DataLoader._batch_len(data[0])
        return 0

    def __iter__(self):
        raw = self._raw_batches()
        if not self.use_buffer_reader:
            for b in raw:
                yield _to_tensors(b)
            return
        # host->device double buffer: keep prefetch_factor batches' transfers
        # in flight (jax device_put is async — overlaps the device step)
        yield from prefetch_to_device(raw, size=self.prefetch_factor)
