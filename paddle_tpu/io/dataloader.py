"""DataLoader: multiprocess workers + host->device prefetch.

Parity target: ``python/paddle/io/dataloader/`` in the reference (DataLoader
with worker subprocesses, shared-memory tensor transport, buffered reader,
IterableDataset worker splitting). TPU redesign (SURVEY §7 hard-part 6 —
keep the MXUs fed):

* workers are ``fork`` subprocesses that ONLY touch numpy (they must never
  initialize the PJRT client); batches cross process boundaries as pickled
  numpy arrays and are wrapped to Tensors in the parent,
* ``use_buffer_reader=True`` adds a host->device double-buffer: the next
  ``prefetch_factor`` batches are ``jax.device_put`` issued ahead of use, so
  the async dispatch overlaps the device step (the TPU analogue of the
  reference's pin-memory + CUDA-stream copy pipeline).
"""

from __future__ import annotations

import collections
import itertools
import multiprocessing as mp
import queue as pyqueue
import time
import traceback
from typing import Any, Callable, List, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "get_worker_info", "default_collate_fn",
           "default_convert_fn", "WorkerInfo", "prefetch_to_device"]


class WorkerInfo:
    def __init__(self, id: int, num_workers: int, seed: int, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker: this worker's (id, num_workers, seed, dataset);
    ``None`` in the main process (reference parity)."""
    return _worker_info


def default_convert_fn(batch):
    return batch


def default_collate_fn(batch: List[Any]):
    """Stack a list of samples into batched numpy arrays (nested structures
    follow the reference: dict -> dict of stacks, tuple -> tuple of stacks)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (np.floating, float)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (np.integer, int)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn(list(fields))
                            for fields in zip(*batch))
    # Tensor / jax array / anything array-like
    try:
        return np.stack([np.asarray(s) for s in batch])
    except Exception:
        return batch


class _ExceptionWrapper:
    def __init__(self, exc):
        self.exc_type = type(exc).__name__
        self.msg = f"{exc}\n{traceback.format_exc()}"

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.exc_type}: {self.msg}")


class _RingSource:
    """Round-robin poll of per-worker shm rings behind a Queue-like .get."""

    def __init__(self, rings):
        self.rings = list(rings)
        self._next = 0

    def get(self, timeout=None):
        import pickle
        import time
        deadline = None if timeout is None else time.time() + timeout
        while True:
            for _ in range(len(self.rings)):
                r = self.rings[self._next]
                self._next = (self._next + 1) % len(self.rings)
                data = r.pop(timeout_ms=2)
                if data is not None:
                    return pickle.loads(data)
            if deadline is not None and time.time() > deadline:
                raise pyqueue.Empty


def _worker_loop(dataset, index_queue, result_queue, collate_fn, init_fn,
                 worker_id, num_workers, seed, iterable, ring=None,
                 all_rings=()):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, seed, dataset)
    np.random.seed(seed % (2 ** 31))
    # forked children inherit owner=True ring handles; they must not destroy
    # the parent's semaphores / shm at interpreter exit (ADVICE r2)
    for r in all_rings:
        try:
            r.disown()
        except Exception:
            pass
    if ring is not None:
        import pickle

        class _RingPut:
            def put(self, item):
                try:
                    ring.push(pickle.dumps(item,
                                           protocol=pickle.HIGHEST_PROTOCOL))
                except ValueError as e:  # payload exceeds slot capacity
                    ring.push(pickle.dumps((item[0], _ExceptionWrapper(e))))
        result_queue = _RingPut()
    try:
        if init_fn is not None:
            init_fn(worker_id)
    except Exception as e:  # init failure poisons every batch
        result_queue.put((-1, _ExceptionWrapper(e)))
        return
    if iterable:
        # stream split: worker w takes items w, w+N, w+2N, ... and batches
        # arrive pre-chunked as (batch_idx, batch_size) requests
        it = itertools.islice(iter(dataset), worker_id, None, num_workers)
        while True:
            req = index_queue.get()
            if req is None:
                return
            bidx, bsize = req
            items = list(itertools.islice(it, bsize))
            if not items:
                result_queue.put((bidx, StopIteration()))
                continue
            try:
                result_queue.put((bidx, collate_fn(items)))
            except Exception as e:
                result_queue.put((bidx, _ExceptionWrapper(e)))
    else:
        while True:
            req = index_queue.get()
            if req is None:
                return
            bidx, indices = req
            try:
                result_queue.put((bidx, collate_fn([dataset[i] for i in indices])))
            except Exception as e:
                result_queue.put((bidx, _ExceptionWrapper(e)))


def _to_tensors(batch, device=None):
    """numpy batch -> Tensor pytree (device transfer happens here; under the
    buffered reader several of these are in flight ahead of consumption)."""
    from ..core.tensor import Tensor, to_tensor
    if isinstance(batch, np.ndarray):
        return to_tensor(batch, place=device)
    if isinstance(batch, dict):
        return {k: _to_tensors(v, device) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(_to_tensors(v, device) for v in batch)
    return batch


def prefetch_to_device(iterable, size: int = 2, device=None):
    """Double-buffered host->device prefetch iterator (the TPU analogue of
    the reference's pin-memory + CUDA-stream copy pipeline, as a standalone
    generator usable over ANY batch iterable, not just DataLoader).

    Keeps ``size`` batches' transfers in flight ahead of the consumer:
    ``jax.device_put`` dispatch is async, so while the device runs step N
    the host is collating batch N+1 ("data" span) and its H2D transfer
    ("h2d" span) streams concurrently — the input pipeline disappears from
    the step time once ``host+h2d < step``. Spans are emitted when
    ``FLAGS_profile_annotations`` is on.

    CPU degradation: there is no host/device overlap to win and "transfers"
    are memcpys, so the buffer collapses to a plain convert-and-yield loop
    (single-buffer fallback) — no extra batch latency in tier-1 tests.

    Batches may be numpy arrays, Tensors, or nested dict/tuple/list pytrees
    of them; ``device`` is an optional Place to pin transfers to.
    """
    from ..profiler import annotate

    it = iter(iterable)
    if not donation_like_backend_supports_overlap():
        for b in it:
            yield _to_tensors(b, device)
        return
    size = max(1, int(size))
    buf = collections.deque()

    def _fill():
        with annotate("data"):
            try:
                b = next(it)
            except StopIteration:
                return False
        with annotate("h2d"):
            buf.append(_to_tensors(b, device))
        return True

    while len(buf) < size and _fill():
        pass
    while buf:
        out = buf.popleft()
        # issue the next transfer BEFORE handing the current batch out, so
        # the H2D copy overlaps the consumer's device step
        _fill()
        yield out


def donation_like_backend_supports_overlap() -> bool:
    """Async-dispatch H2D overlap exists off-CPU (same backend split as
    jit.train_step.donation_supported; kept separate so io never imports
    jit)."""
    import jax
    return jax.default_backend() not in ("cpu",)


class DataLoader:
    """ref: paddle.io.DataLoader (return_list=True semantics only — the
    legacy feed-dict mode targets the static graph executor, which this
    framework replaces with jit; pass ``feed_list`` for API compat, it is
    ignored)."""

    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler=None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable] = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: float = 0, worker_init_fn: Optional[Callable] = None,
                 persistent_workers: bool = False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = bool(use_shared_memory)
        self.shm_slot_bytes = 32 << 20
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            if batch_sampler is not None or shuffle:
                raise ValueError(
                    "IterableDataset does not accept batch_sampler/shuffle")
            self.batch_size = int(batch_size)
            self.drop_last = bool(drop_last)
            self.batch_sampler = None
        elif batch_sampler is not None:
            if batch_size != 1 or shuffle or drop_last:
                raise ValueError(
                    "batch_sampler is mutually exclusive with "
                    "batch_size/shuffle/drop_last")
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self.batch_size = int(batch_size)

    def __len__(self):
        if self._iterable:
            raise TypeError("DataLoader over an IterableDataset has no length")
        return len(self.batch_sampler)

    # -- iteration ----------------------------------------------------------

    def _raw_batches(self):
        """Yield collated numpy batches (single- or multi-process)."""
        if self.num_workers == 0:
            if self._iterable:
                it = iter(self.dataset)
                while True:
                    items = list(itertools.islice(it, self.batch_size))
                    if not items or (self.drop_last and
                                     len(items) < self.batch_size):
                        return
                    yield self.collate_fn(items)
            else:
                for indices in self.batch_sampler:
                    yield self.collate_fn([self.dataset[i] for i in indices])
            return
        yield from self._multiprocess_batches()

    def _make_rings(self, nw):
        """Shared-memory transport (native C++ ring; reference shm parity).
        Falls back to mp.Queue when the native lib is unavailable."""
        if not self.use_shared_memory:
            return None
        try:
            import os
            from ..native import ShmRing
            tag = f"/pt_dl_{os.getpid()}_{id(self) & 0xffffff}"
            return [ShmRing(f"{tag}_{w}", slots=4,
                            slot_bytes=self.shm_slot_bytes)
                    for w in range(nw)]
        except Exception:
            return None

    def _multiprocess_batches(self):
        ctx = mp.get_context("fork")  # workers reuse the parent's dataset
        nw = self.num_workers
        result_queue = ctx.Queue()
        rings = self._make_rings(nw)
        result_src = _RingSource(rings) if rings else result_queue
        index_queues, workers = [], []
        base_seed = np.random.randint(0, 2 ** 31 - 1)
        for w in range(nw):
            iq = ctx.Queue()
            p = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, iq, result_queue, self.collate_fn,
                      self.worker_init_fn, w, nw, base_seed + w,
                      self._iterable, rings[w] if rings else None,
                      tuple(rings) if rings else ()),
                daemon=True)
            p.start()
            index_queues.append(iq)
            workers.append(p)
        try:
            if self._iterable:
                yield from self._mp_iterable(index_queues, result_src, nw,
                                             workers)
            else:
                yield from self._mp_map(index_queues, result_src, nw,
                                        workers)
        finally:
            for iq in index_queues:
                try:
                    iq.put(None)
                except Exception:
                    pass
            for p in workers:
                p.join(timeout=1.0)
                if p.is_alive():
                    p.terminate()
            if rings:
                for r in rings:
                    r.close()

    def _get(self, result_queue, workers=()):
        """Queue get with a liveness watchdog: wait in short slices and fail
        fast with a descriptive error when a worker died (OOM-kill/segfault)
        instead of blocking forever (the reference DataLoader's watchdog)."""
        deadline = (None if not self.timeout
                    else time.monotonic() + self.timeout)
        while True:
            slice_t = 1.0
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s waiting "
                        f"for a worker batch")
                slice_t = min(slice_t, left)
            try:
                return result_queue.get(timeout=slice_t)
            except pyqueue.Empty:
                dead = [(i, p.exitcode) for i, p in enumerate(workers)
                        if not p.is_alive()]
                if dead:
                    # final drain: a worker may have enqueued its result (or
                    # the real exception) just before exiting — surface that
                    # instead of a misleading died-unexpectedly error
                    try:
                        return result_queue.get(timeout=0.2)
                    except pyqueue.Empty:
                        pass
                    descr = ", ".join(f"worker {i} exit code {c}"
                                      for i, c in dead)
                    raise RuntimeError(
                        f"DataLoader worker(s) died unexpectedly ({descr}) — "
                        f"likely killed by OOM or a segfault in dataset "
                        f"code; the remaining batch will never arrive"
                    ) from None

    def _mp_map(self, index_queues, result_queue, nw, workers=()):
        batches = list(self.batch_sampler)
        depth = min(len(batches), self.prefetch_factor * nw)
        nxt = 0
        for nxt in range(depth):
            index_queues[nxt % nw].put((nxt, batches[nxt]))
        nxt = depth
        reorder = {}
        for want in range(len(batches)):
            while want not in reorder:
                bidx, data = self._get(result_queue, workers)
                if bidx == -1 or isinstance(data, _ExceptionWrapper):
                    if isinstance(data, _ExceptionWrapper):
                        data.reraise()
                reorder[bidx] = data
            data = reorder.pop(want)
            if nxt < len(batches):
                index_queues[nxt % nw].put((nxt, batches[nxt]))
                nxt += 1
            yield data

    def _mp_iterable(self, index_queues, result_queue, nw, workers=()):
        # request batches round-robin; a worker answering StopIteration is
        # retired, remaining workers drain their stream tails
        active = set(range(nw))
        bidx = 0
        inflight = collections.deque()
        depth = self.prefetch_factor * nw

        def request():
            nonlocal bidx
            if not active:
                return False
            w = bidx % nw
            if w not in active:
                w = next(iter(active))
            index_queues[w].put((bidx, self.batch_size))
            inflight.append(bidx)
            bidx += 1
            return True

        for _ in range(depth):
            request()
        reorder = {}
        want = 0
        done = set()
        while inflight:
            while inflight[0] not in reorder:
                i, data = self._get(result_queue, workers)
                if isinstance(data, _ExceptionWrapper):
                    data.reraise()
                reorder[i] = data
            i = inflight.popleft()
            data = reorder.pop(i)
            if isinstance(data, StopIteration):
                done.add(i)
                active.discard(i % nw)
                continue
            if len(data if isinstance(data, list) else [0]) and request():
                pass
            if self.drop_last and self._batch_len(data) < self.batch_size:
                continue
            yield data

    @staticmethod
    def _batch_len(data):
        if isinstance(data, np.ndarray):
            return data.shape[0]
        if isinstance(data, dict):
            return DataLoader._batch_len(next(iter(data.values())))
        if isinstance(data, (tuple, list)) and data:
            return DataLoader._batch_len(data[0])
        return 0

    def __iter__(self):
        raw = self._raw_batches()
        if not self.use_buffer_reader:
            for b in raw:
                yield _to_tensors(b)
            return
        # host->device double buffer: keep prefetch_factor batches' transfers
        # in flight (jax device_put is async — overlaps the device step)
        yield from prefetch_to_device(raw, size=self.prefetch_factor)
