"""Dataset types.

Parity target: ``python/paddle/io/dataloader/dataset.py`` in the reference
(Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
Subset, ConcatDataset, random_split).
"""

from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    """Map-style dataset: implement ``__getitem__`` and ``__len__``."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __getitem__")

    def __len__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __len__")


class IterableDataset(Dataset):
    """Stream-style dataset: implement ``__iter__``; workers split the stream
    via ``get_worker_info()`` (reference parity)."""

    def __iter__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __iter__")

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        # TypeError, not RuntimeError: list()/length_hint probe __len__ and
        # only swallow TypeError
        raise TypeError("IterableDataset has no static length")


class TensorDataset(Dataset):
    """Wrap equal-first-dim tensors/arrays; item i is the tuple of row i."""

    def __init__(self, tensors: Sequence):
        from ..core.tensor import Tensor
        if not tensors:
            raise ValueError("TensorDataset needs at least one tensor")
        arrays = []
        for t in tensors:
            arrays.append(np.asarray(t.numpy() if isinstance(t, Tensor) else t))
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise ValueError("TensorDataset tensors must share dim 0 "
                                 f"({a.shape[0]} != {n})")
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    """Zip several map-style datasets; item i concatenates their fields."""

    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ComposeDataset needs at least one dataset")
        lens = [len(d) for d in self.datasets]
        if len(set(lens)) != 1:
            raise ValueError(f"ComposeDataset lengths differ: {lens}")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    """Concatenate iterable datasets into one stream."""

    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    """Concatenate map-style datasets end to end."""

    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(idx)
        ds = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds == 0 else self.cumulative_sizes[ds - 1]
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None) -> List[Subset]:
    """Split by lengths (ints) or fractions summing to 1 (reference parity)."""
    n = len(dataset)
    ls = list(lengths)
    if ls and all(isinstance(x, float) for x in ls):
        if abs(sum(ls) - 1.0) > 1e-6:
            raise ValueError("random_split fractions must sum to 1")
        counts = [int(np.floor(n * f)) for f in ls]
        for i in range(n - sum(counts)):
            counts[i % len(counts)] += 1
        ls = counts
    if sum(ls) != n:
        raise ValueError(f"random_split lengths sum {sum(ls)} != dataset {n}")
    rng = generator if generator is not None else np.random.default_rng()
    perm = rng.permutation(n).tolist()
    out, ofs = [], 0
    for l in ls:
        out.append(Subset(dataset, perm[ofs:ofs + l]))
        ofs += l
    return out
