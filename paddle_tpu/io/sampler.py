"""Samplers.

Parity target: ``python/paddle/io/dataloader/sampler.py`` and
``batch_sampler.py`` in the reference (Sampler, SequenceSampler,
RandomSampler, WeightedRandomSampler, BatchSampler, DistributedBatchSampler).
The distributed sampler shards by rank exactly like the reference (padding to
even length, per-epoch shuffle seed).
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
           "SubsetRandomSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement: bool = False,
                 num_samples: Optional[int] = None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        if not replacement and num_samples is not None and \
                num_samples > len(data_source):
            raise ValueError("num_samples > dataset size without replacement")

    @property
    def num_samples(self) -> int:
        return self._num_samples if self._num_samples is not None \
            else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = self.generator or np.random.default_rng()
        if self.replacement:
            yield from rng.integers(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[: self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__(None)
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        rng = self.generator or np.random.default_rng()
        for i in rng.permutation(len(self.indices)):
            yield self.indices[i]

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples: int, replacement: bool = True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        if (self.weights < 0).any():
            raise ValueError("weights must be non-negative")
        self.num_samples = int(num_samples)
        self.replacement = replacement
        if not replacement and num_samples > len(self.weights):
            raise ValueError("num_samples > #weights without replacement")

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        idx = rng.choice(len(self.weights), self.num_samples,
                         replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle: bool = False, batch_size: int = 1,
                 drop_last: bool = False):
        super().__init__(dataset)
        if sampler is not None:
            if dataset is not None:
                raise ValueError("BatchSampler: pass dataset OR sampler")
            self.sampler = sampler
        elif dataset is not None:
            self.sampler = RandomSampler(dataset) if shuffle \
                else SequenceSampler(dataset)
        else:
            raise ValueError("BatchSampler needs a dataset or a sampler")
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)

    def __iter__(self):
        batch: List[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last \
            else (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shard batches by data-parallel rank (ref: DistributedBatchSampler —
    pad to a rank-divisible length, per-epoch seeded shuffle, ``set_epoch``)."""

    def __init__(self, dataset, batch_size: int, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = False,
                 drop_last: bool = False):
        self.dataset = dataset
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self.shuffle = bool(shuffle)
        if num_replicas is None or rank is None:
            from ..distributed.topology import get_hybrid_communicate_group
            hcg = get_hybrid_communicate_group()
            if num_replicas is None:
                num_replicas = hcg.get_data_parallel_world_size()
            if rank is None:
                r = hcg.get_data_parallel_rank()
                rank = int(r) if isinstance(r, int) else 0
        self.nranks = int(num_replicas)
        self.local_rank = int(rank)
        if not 0 <= self.local_rank < self.nranks:
            raise ValueError(f"rank {rank} out of range for {num_replicas}")
        self.epoch = 0
        n = len(dataset)
        self.num_samples = int(math.ceil(n / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        self.epoch = int(epoch)

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad so every rank sees the same number of samples
        indices += indices[: self.total_size - n]
        local = indices[self.local_rank:self.total_size:self.nranks]
        batch: List[int] = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
