"""``paddle.jit`` — dynamic-to-static compilation (see api.py / trace.py)."""

from .api import (InputSpec, StaticFunction, TranslatedLayer, enable_to_static,
                  ignore_module, load, not_to_static, save, to_static)
from .control_flow import cond, fori_loop, scan, while_loop
from .train_step import TrainStep, donation_supported, jit_step, make_train_step
from . import dy2static

__all__ = ["InputSpec", "StaticFunction", "TranslatedLayer", "enable_to_static",
           "ignore_module", "load", "not_to_static", "save", "to_static",
           "cond", "fori_loop", "scan", "while_loop",
           "TrainStep", "make_train_step", "jit_step", "donation_supported"]
