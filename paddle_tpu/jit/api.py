"""``paddle.jit`` public API: to_static / save / load / InputSpec.

Parity target: ``python/paddle/jit/api.py`` (``to_static``, ``jit.save``,
``jit.load``) and ``dy2static/program_translator.py`` (``StaticFunction`` signature
cache) in the reference. TPU redesign: programs are jax.jit-compiled XLA
executables (see trace.py); ``jit.save`` exports a StableHLO artifact via
``jax.export`` instead of a ProgramDesc, with weights in a separate pickle
(.pdmodel/.pdiparams file-pair parity).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import canonical_dtype, get_default_dtype
from ..core.tensor import Tensor, _wrap_value
from .trace import CompiledProgram

__all__ = ["InputSpec", "StaticFunction", "to_static", "not_to_static", "ignore_module",
           "save", "load", "TranslatedLayer", "enable_to_static"]

_to_static_enabled = True


def enable_to_static(flag: bool = True):
    """ProgramTranslator().enable() parity — globally bypass compilation."""
    global _to_static_enabled
    _to_static_enabled = bool(flag)


class InputSpec:
    """paddle.static.InputSpec parity. ``None`` dims are symbolic (batch etc.)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = canonical_dtype(dtype) or get_default_dtype()
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, t: Tensor, name=None):
        return cls(t.shape, t.dtype, name or t.name, t.stop_gradient)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def _example(self) -> Tensor:
        shape = tuple(1 if (d is None or d < 0) else int(d) for d in self.shape)
        t = _wrap_value(jnp.zeros(shape, self.dtype),
                        stop_gradient=self.stop_gradient)
        if self.name:
            t.name = self.name
        return t

    def _export_spec(self, scope):
        """jax.ShapeDtypeStruct with symbolic dims for jax.export."""
        dims = []
        for i, d in enumerate(self.shape):
            if d is None or (isinstance(d, int) and d < 0):
                dims.append(scope.setdefault(f"d{len(scope)}", None) or f"d{i}")
            else:
                dims.append(d)
        if any(isinstance(d, str) for d in dims):
            from jax import export as jexport
            sym = jexport.symbolic_shape(
                ",".join(str(d) for d in dims))
            return jax.ShapeDtypeStruct(sym, self.dtype)
        return jax.ShapeDtypeStruct(tuple(dims), self.dtype)


class StaticFunction:
    """Signature-cached compiled wrapper (ProgramTranslator StaticFunction parity).

    Call 1 per function runs eagerly (lets lazy state — optimizer accumulators,
    lazily-built sublayers — initialize with real values); later calls hit the
    compiled program cache keyed by (tree structure, shapes, dtypes, training flags).
    """

    def __init__(self, function, input_spec=None, donate_states=False,
                 layer=None, ast_target=None):
        self._fn = function
        self._input_spec = input_spec
        self._donate = donate_states
        self._layer = layer
        self._programs = {}
        self._warmed_up = False
        self._ast_fn = None       # dy2static-transformed fallback (lazy)
        self._ast_target = ast_target  # what to transform (Layer.forward)

    @property
    def _train_flags(self):
        if self._layer is None:
            return ()
        return tuple(m.training for m in self._layer.sublayers(include_self=True))

    def _sig(self, args, kwargs):
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        parts = []
        for l in leaves:
            if isinstance(l, Tensor):
                parts.append(("T", tuple(l.shape), str(l.dtype)))
            elif isinstance(l, (jax.Array, np.ndarray)):
                parts.append(("A", tuple(l.shape), str(l.dtype)))
            else:
                try:
                    parts.append(("S", hash(l)))
                except TypeError:
                    parts.append(("S", repr(l)))
        return (treedef, tuple(parts), self._train_flags)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled or autograd_under_trace():
            return self._fn(*args, **kwargs)
        if not self._warmed_up:
            self._warmed_up = True
            return self._fn(*args, **kwargs)
        key = self._sig(args, kwargs)
        prog = self._programs.get(key)
        if prog is None:
            fn = self._ast_fn or self._fn
            try:
                prog = CompiledProgram(fn, args, kwargs,
                                       donate_states=self._donate,
                                       layer=self._layer)
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError) as e:
                # dy2static fallback (the reference's transformer tier):
                # rewrite tensor-dependent if/while to lax control flow
                # and retrace once
                if self._ast_fn is None:
                    import functools
                    import inspect

                    from .dy2static import ast_transform
                    target = self._ast_target or self._fn
                    try:
                        if inspect.ismethod(target):
                            # Layer case: transform the underlying forward
                            # and re-bind its instance
                            tf = ast_transform(target.__func__)
                            cand = functools.partial(tf, target.__self__)
                        else:
                            cand = ast_transform(target)
                        prog = CompiledProgram(cand, args, kwargs,
                                               donate_states=self._donate,
                                               layer=self._layer)
                    except Exception as e2:
                        raise RuntimeError(
                            "to_static: data-dependent Python control flow "
                            "(if/while on a tensor value) cannot be traced, "
                            "and the dy2static AST rewrite could not lower "
                            "it (branches with return/break/continue or "
                            "object mutation are out of its scope). Use "
                            "paddle_tpu.jit.cond / while_loop / scan "
                            "explicitly, or fall back to eager mode.\n"
                            f"trace error: {e}\n"
                            f"dy2static: {e2}") from None
                    # only adopt the transformed fn once it COMPILED — a
                    # broken transform must not poison later calls
                    self._ast_fn = cand
                else:
                    raise RuntimeError(
                        "to_static: data-dependent Python control flow "
                        "remains after the dy2static rewrite. Use "
                        "paddle_tpu.jit.cond / while_loop / scan.\n"
                        f"original error: {e}") from None
            self._programs[key] = prog
        return prog(args, kwargs)

    # paddle API compat
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except (OSError, TypeError):
            return "<source unavailable>"

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    def rollback(self):
        return self._fn


def autograd_under_trace() -> bool:
    """True when already inside a trace (nested to_static collapses to inline)."""
    from ..core.tensor import _trace_hook
    return _trace_hook.ctx is not None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, donate_states=False, **kwargs):
    """``@paddle.jit.to_static`` parity. Also accepts a Layer instance.

    ``backend="sot"`` selects the bytecode-tier capture (``jit/sot.py``):
    guard-based path specialization with graph-break eager fallback — use it
    when the function has data-dependent control flow beyond the AST tier's
    scope (return inside a tensor branch, data-dependent ``for``, gradients
    through a tensor ``while``). Default (None) = trace + AST-rewrite
    fallback."""

    def decorate(fn):
        from ..nn.layer import Layer

        if backend == "sot":
            from .sot import SOTFunction
            if isinstance(fn, Layer):
                layer = fn
                orig_forward = layer.forward
                sf = SOTFunction(lambda *a, **k: orig_forward(*a, **k),
                                 input_spec, donate_states, layer=layer,
                                 guard_target=orig_forward)
                layer.forward = sf
                layer._static_function = sf
                layer._orig_forward = orig_forward
                return layer
            sf = SOTFunction(fn, input_spec, donate_states)
            import functools
            functools.update_wrapper(sf, fn)
            return sf
        if backend not in (None, "CINN", "cinn"):
            raise ValueError(f"to_static: unknown backend {backend!r}; "
                             "options: None (trace+AST), 'sot'")

        if isinstance(fn, Layer):
            layer = fn
            orig_forward = layer.forward
            sf = StaticFunction(lambda *a, **k: orig_forward(*a, **k),
                                input_spec, donate_states, layer=layer,
                                ast_target=orig_forward)
            layer.forward = sf
            layer._static_function = sf
            layer._orig_forward = orig_forward
            return layer
        sf = StaticFunction(fn, input_spec, donate_states)
        import functools
        functools.update_wrapper(sf, fn)
        return sf

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    """Marker: never compile this function (paddle.jit.not_to_static parity)."""
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    return None


# ---------------------------------------------------------------------------
# save / load (StableHLO artifact + weights pickle)
# ---------------------------------------------------------------------------

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs):
    """``paddle.jit.save`` parity: serialize an inference program + weights.

    The program is the layer's forward traced in eval mode with parameters and
    buffers lifted to explicit inputs, exported to portable StableHLO bytes
    (``jax.export``), so it can be reloaded and run without the python model code.
    """
    from ..core import autograd as _ag
    from ..nn.layer import Layer
    from jax import export as jexport

    if isinstance(layer, StaticFunction):
        fn = layer._fn
        model_layer = layer._layer
    elif isinstance(layer, Layer):
        model_layer = layer
        fn = getattr(layer, "_orig_forward", None) or layer.forward
        if isinstance(fn, StaticFunction):
            fn = fn._fn
    else:
        model_layer, fn = None, layer

    if input_spec is None:
        spec_src = getattr(layer, "_static_function", None)
        input_spec = getattr(spec_src, "_input_spec", None)
    if input_spec is None:
        raise ValueError("jit.save requires input_spec (list of InputSpec or "
                         "example Tensors)")
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in input_spec]

    # collect weights (params + buffers), fixed order
    named = []
    if model_layer is not None:
        was_training = model_layer.training
        model_layer.eval()
        named = list(model_layer.named_parameters()) + \
            list(model_layer.named_buffers())
    names = [n for n, _ in named]
    tensors = [t for _, t in named]

    def pure(param_vals, arg_vals):
        saved = [t._raw for t in tensors]
        for t, v in zip(tensors, param_vals):
            t._raw = v
        try:
            with _ag.no_grad():
                args = [_wrap_value(v, stop_gradient=True) for v in arg_vals]
                out = fn(*args)
            leaves, _ = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            return [l._raw if isinstance(l, Tensor) else jnp.asarray(l)
                    for l in leaves]
        finally:
            for t, v in zip(tensors, saved):
                t._raw = v

    scope: dict = {}
    param_specs = [jax.ShapeDtypeStruct(tuple(t.shape), t.dtype) for t in tensors]
    arg_specs = [s._export_spec(scope) for s in specs]
    exported = jexport.export(jax.jit(pure))(param_specs, arg_specs)
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + _MODEL_SUFFIX, "wb") as f:
        pickle.dump({"stablehlo": blob, "param_names": names,
                     "input_specs": [(s.shape, str(np.dtype(s.dtype).name),
                                      s.name) for s in specs]}, f)
    with open(path + _PARAMS_SUFFIX, "wb") as f:
        pickle.dump({n: np.asarray(t._raw) for n, t in zip(names, tensors)}, f)
    if model_layer is not None and was_training:
        model_layer.train()


class TranslatedLayer:
    """Reloaded inference program (paddle.jit.TranslatedLayer parity)."""

    def __init__(self, exported, params: List, param_names: List[str]):
        self._exported = exported
        self._params = params
        self._param_names = param_names

    def __call__(self, *args):
        arg_vals = [a._raw if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in args]
        outs = self._exported.call(self._params, arg_vals)
        wrapped = [_wrap_value(o) for o in outs]
        return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is an inference artifact; re-train "
                           "from the original model code")

    def parameters(self):
        return [_wrap_value(p) for p in self._params]

    def state_dict(self):
        return {n: _wrap_value(p) for n, p in zip(self._param_names, self._params)}


def load(path: str, **configs) -> TranslatedLayer:
    """``paddle.jit.load`` parity: reload a saved inference artifact."""
    from jax import export as jexport

    with open(path + _MODEL_SUFFIX, "rb") as f:
        meta = pickle.load(f)
    with open(path + _PARAMS_SUFFIX, "rb") as f:
        weights = pickle.load(f)
    exported = jexport.deserialize(meta["stablehlo"])
    params = [jnp.asarray(weights[n]) for n in meta["param_names"]]
    tl = TranslatedLayer(exported, params, meta["param_names"])
    # consumers (inference.Predictor) read these without re-unpickling the
    # whole artifact (the stablehlo blob dominates the file)
    tl._input_specs = meta.get("input_specs", [])
    return tl
