"""Structured control flow over Tensors for use inside ``to_static``.

Parity target: the reference's dy2static lowering of python ``if``/``for``/``while``
to ``cond``/``while_loop`` ops (``python/paddle/jit/dy2static/transformers/``,
``paddle.static.nn.cond/while_loop``). TPU redesign: these are thin Tensor wrappers
over ``lax.cond`` / ``lax.while_loop`` / ``lax.scan`` — the XLA-native control-flow
primitives — usable both eagerly and under a trace. ``cond`` and ``scan`` are
differentiable through the tape (the recorded vjp differentiates the whole lax
primitive); ``while_loop`` is forward-only (XLA has no reverse-mode while; use scan
for differentiable loops — same limitation the reference documents for dynamic
shapes under CINN).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core import autograd
from ..core.tensor import Tensor, _wrap_value
from ..core.dispatch import forward_op

__all__ = ["cond", "while_loop", "scan", "fori_loop"]


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_tree(tree):
    return jax.tree_util.tree_map(_wrap_value, tree)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """paddle.static.nn.cond parity, differentiable w.r.t. tensor operands."""
    pred_tensor = pred if isinstance(pred, Tensor) else _wrap_value(jnp.asarray(pred))
    flat_ops, tree = jax.tree_util.tree_flatten(
        operands, is_leaf=lambda x: isinstance(x, Tensor))
    tensor_slots = [i for i, o in enumerate(flat_ops) if isinstance(o, Tensor)]
    tensor_args = [flat_ops[i] for i in tensor_slots]

    def impl(p, *vals):
        rebuilt = list(flat_ops)
        for i, v in zip(tensor_slots, vals):
            rebuilt[i] = v

        def run(fn):
            def branch(rb):
                leaves = [(_wrap_value(v) if k in tensor_slots else v)
                          for k, v in enumerate(rb)]
                ops = jax.tree_util.tree_unflatten(tree, leaves)
                with autograd.no_grad():
                    out = fn(*ops) if ops else fn()
                return _unwrap_tree(out)
            return branch

        return lax.cond(jnp.asarray(p).astype(bool).reshape(()),
                        run(true_fn), run(false_fn), rebuilt)

    return forward_op("cond", impl, [pred_tensor] + tensor_args)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars):
    """paddle.static.nn.while_loop parity (forward-only)."""
    is_seq = isinstance(loop_vars, (list, tuple))
    vals = _unwrap_tree(tuple(loop_vars) if is_seq else (loop_vars,))

    def c(vs):
        out = cond_fn(*_wrap_tree(vs))
        return (out._value if isinstance(out, Tensor)
                else jnp.asarray(out)).reshape(())

    def b(vs):
        out = body_fn(*_wrap_tree(vs))
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return _unwrap_tree(tuple(out))

    with autograd.no_grad():
        res = lax.while_loop(c, b, vals)
    wrapped = tuple(_wrap_value(v) for v in res)
    return list(wrapped) if is_seq else wrapped[0]


def scan(body_fn: Callable, init, xs=None, length=None, reverse=False):
    """Differentiable loop: carry, ys = scan(f, init, xs) (lax.scan over Tensors)."""
    init_vals = _unwrap_tree(init)
    xs_vals = _unwrap_tree(xs) if xs is not None else None
    carry_tensors = [t for t in jax.tree_util.tree_leaves(
        init, is_leaf=lambda x: isinstance(x, Tensor)) if isinstance(t, Tensor)]
    xs_tensors = [t for t in jax.tree_util.tree_leaves(
        xs, is_leaf=lambda x: isinstance(x, Tensor)) if isinstance(t, Tensor)] \
        if xs is not None else []

    init_tree = jax.tree_util.tree_structure(
        init, is_leaf=lambda x: isinstance(x, Tensor))

    def impl(*flat):
        n = len(carry_tensors)
        c0 = jax.tree_util.tree_unflatten(init_tree, flat[:n])
        x_leaves = flat[n:]
        if xs is not None:
            xs_tree = jax.tree_util.tree_structure(
                xs, is_leaf=lambda x: isinstance(x, Tensor))
            xs_full = jax.tree_util.tree_unflatten(xs_tree, x_leaves)
        else:
            xs_full = None

        def step(carry, x):
            with autograd.no_grad():
                out = body_fn(_wrap_tree(carry),
                              _wrap_tree(x) if x is not None else None)
            new_carry, y = out
            return _unwrap_tree(new_carry), _unwrap_tree(y)

        return lax.scan(step, c0, xs_full, length=length, reverse=reverse)

    carry, ys = forward_op("scan", impl, carry_tensors + xs_tensors)
    return carry, ys


def fori_loop(lower, upper, body_fn: Callable, init):
    """lax.fori_loop over Tensors (forward-only)."""
    init_vals = _unwrap_tree(init)

    def b(i, vs):
        out = body_fn(_wrap_value(jnp.asarray(i)), _wrap_tree(vs))
        return _unwrap_tree(out)

    with autograd.no_grad():
        res = lax.fori_loop(int(lower) if not isinstance(lower, Tensor) else
                            lower._value,
                            int(upper) if not isinstance(upper, Tensor) else
                            upper._value, b, init_vals)
    return _wrap_tree(res)
