"""AST-level dy2static: tensor-dependent Python control flow -> lax.

Parity target: ``python/paddle/jit/dy2static/transformers/`` in the
reference (IfElseTransformer / LoopTransformer rewriting ``if``/``while``
into ``convert_ifelse``/``convert_while`` calls, with the SOT bytecode tier
above it). TPU redesign: the rewrite targets the XLA-native control-flow
primitives already wrapped in ``jit.control_flow`` (``lax.cond`` /
``lax.while_loop``); the runtime ``convert_*`` helpers dispatch on the
predicate's type, so python-bool conditions keep exact eager semantics and
only Tensor conditions lower to lax.

Engagement is the reference's fallback UX: ``to_static`` traces the
function as-is first, and on a data-dependent-control-flow trace error
retries with the transformed function (StaticFunction.__call__).

Scope (documented): ``if``/``elif``/``else`` and ``while`` whose branches
assign plain local names; branches containing ``return``/``break``/
``continue`` or attribute/subscript stores are left untouched (they only
fail if actually tensor-dependent, with the original error). Counted
``for i in range(...)`` loops with clean bodies lower to ``jit.scan``
(one trace regardless of trip count, differentiable; shape-varying
carries fall back to python unrolling). ``while`` lowers to
``lax.while_loop`` and is forward-only — the SOT tier
(``to_static(backend="sot")``) covers everything beyond this scope.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, Tuple

__all__ = ["ast_transform", "convert_ifelse", "convert_while",
           "convert_range_for", "Dy2StaticError"]


class Dy2StaticError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# runtime dispatch helpers (ref: paddle.jit.dy2static.convert_ifelse/...)
# ---------------------------------------------------------------------------

def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, ins: Tuple):
    """Tensor predicate -> lax.cond through jit.control_flow (grads flow
    through the threaded ``ins``); python predicate -> plain if. The branch
    fns return a bare value for a single rewritten name and a tuple for
    several — the call-site target mirrors that exactly."""
    from ..core.tensor import Tensor
    if isinstance(pred, Tensor):
        from .control_flow import cond
        return cond(pred, true_fn, false_fn, *ins)
    return true_fn(*ins) if pred else false_fn(*ins)


def convert_range_for(range_args: Tuple, body_fn: Callable,
                      loop_vars: Tuple) -> Tuple:
    """Counted ``for i in range(...)`` over tensor-carried loop vars ->
    ``jit.scan`` (differentiable, ONE trace regardless of trip count — the
    r3 VERDICT weak-#3 rewrite); python-only carries, or bodies whose
    carried shapes change across iterations (concat-style accumulators),
    fall back to the plain python loop (= the old trace-unrolling
    semantics).
    """
    from ..core.tensor import Tensor
    n_range = range(*[int(a) for a in range_args])
    has_tensor = any(isinstance(v, Tensor) for v in loop_vars)
    if has_tensor and len(n_range) >= 2:
        from ..core.tensor import to_tensor
        import numpy as _np

        def step(carry, idx):
            return tuple(body_fn(idx, *carry)), ()
        try:
            from .control_flow import scan
            carry, _ = scan(step, tuple(loop_vars),
                            xs=to_tensor(_np.asarray(list(n_range),
                                                     _np.int32)))
            return tuple(carry)
        except Exception:
            pass     # shape-varying carry etc. — unroll like before
    vs = tuple(loop_vars)
    for i in n_range:
        vs = tuple(body_fn(i, *vs))
    return vs


def convert_while(cond_fn: Callable, body_fn: Callable,
                  loop_vars: Tuple) -> Tuple:
    """Tensor condition -> lax.while_loop (forward-only; REFUSES when a
    loop var wants gradients — silent zero-grad is worse than the loud
    error pointing at jit.scan); python condition -> plain while."""
    from ..core import autograd
    from ..core.tensor import Tensor
    first = cond_fn(*loop_vars)
    if isinstance(first, Tensor):
        if autograd.is_grad_enabled() and any(
                isinstance(v, Tensor) and not v.stop_gradient
                for v in loop_vars):
            raise Dy2StaticError(
                "tensor-dependent `while` lowers to lax.while_loop, which "
                "is forward-only — gradients through the loop would be "
                "silently zero. Rewrite the loop with paddle_tpu.jit.scan "
                "(differentiable), or mark the loop vars stop_gradient")
        from .control_flow import while_loop
        res = while_loop(cond_fn, lambda *vs: tuple(body_fn(*vs)),
                         list(loop_vars))
        return tuple(res)
    # python predicate: reuse the probe evaluation — an impure condition
    # must run exactly once per iteration check
    vs = tuple(loop_vars)
    res = first
    while res:
        vs = tuple(body_fn(*vs))
        res = cond_fn(*vs)
    return vs


# ---------------------------------------------------------------------------
# static analysis
# ---------------------------------------------------------------------------


def _param_names(args: ast.arguments) -> set:
    names = {a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _assigned_names(stmts) -> set:
    """Plain local names bound by the statements (nested defs excluded)."""
    names = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # don't descend into nested defs
            names.add(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)

    for s in stmts:
        V().visit(s)
    return names


def _loaded_names(node) -> set:
    """Names the code READS from the enclosing scope. Scope-aware: a load
    inside a nested def of a name that nested def itself binds (param or
    assignment) is local to it and not counted."""
    names = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, n):
            if isinstance(n.ctx, ast.Load):
                names.add(n.id)

        def visit_AugAssign(self, n):
            # `y += 1` reads y even though its target ctx is Store
            if isinstance(n.target, ast.Name):
                names.add(n.target.id)
            self.generic_visit(n)

        def visit_FunctionDef(self, n):
            own = _param_names(n.args) | _assigned_names(n.body)
            inner = _loaded_names(ast.Module(body=list(n.body),
                                             type_ignores=[]))
            names.update(inner - own)

        visit_AsyncFunctionDef = visit_FunctionDef

    V().visit(node)
    return names


def _has_jump(stmts) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, n):
            self.found = True

        def visit_Break(self, n):
            self.found = True

        def visit_Continue(self, n):
            self.found = True

        def visit_FunctionDef(self, n):  # jumps inside nested defs are fine
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _has_object_store(stmts) -> bool:
    """Side effects we cannot thread through lax branches: attribute/
    subscript stores, and STATEMENT-level calls (``cache.append(x)``) —
    lax.cond traces BOTH branches, so a mutating call would run regardless
    of the predicate. (Value-producing calls inside assignments are assumed
    pure, the same contract jax.lax.cond itself imposes.)"""
    class V(ast.NodeVisitor):
        found = False

        def visit_Attribute(self, n):
            if isinstance(n.ctx, ast.Store):
                self.found = True
            self.generic_visit(n)

        def visit_Subscript(self, n):
            if isinstance(n.ctx, ast.Store):
                self.found = True
            self.generic_visit(n)

        def visit_Expr(self, n):
            if isinstance(n.value, ast.Call):
                self.found = True   # bare call: presumed side-effecting
            self.generic_visit(n)

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _definitely_bound(stmts) -> set:
    """Names guaranteed bound after the statements run on EVERY path — a
    name assigned only inside one if-branch or a possibly-zero-iteration
    loop is NOT definite (reading it later may raise in eager python, so
    the rewrite must not turn it into an unconditional call-site load)."""
    out = set()
    for s in stmts:
        if isinstance(s, ast.If):
            out |= (_definitely_bound(s.body) & _definitely_bound(s.orelse))
        elif isinstance(s, (ast.While, ast.For, ast.Try)):
            pass                      # may run zero times / partially
        else:
            out |= _assigned_names([s])
    return out


def _free_reads(stmts, pre_bound=()) -> set:
    """Names READ before being written, walking statements in order — a
    branch-local temporary (``t = ...; y = t + 1``) is not a free read and
    must not become a call-site input."""
    bound = set(pre_bound)
    free = set()

    def reads(node):
        free.update(_loaded_names(node) - bound)

    for s in stmts:
        if isinstance(s, ast.Assign):
            reads(s.value)
            bound |= _assigned_names([s])
        elif isinstance(s, ast.AugAssign):
            reads(s.value)
            if isinstance(s.target, ast.Name) and s.target.id not in bound:
                free.add(s.target.id)
            bound |= _assigned_names([s])
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            own = _param_names(s.args) | _assigned_names(s.body)
            free.update((_loaded_names(ast.Module(body=list(s.body),
                                                  type_ignores=[])) - own)
                        - bound)
            bound.add(s.name)
        elif isinstance(s, ast.If):
            reads(s.test)
            free.update(_free_reads(s.body, bound))
            free.update(_free_reads(s.orelse, bound))
            bound |= (_assigned_names(s.body) | _assigned_names(s.orelse))
        else:
            reads(s)
            bound |= _assigned_names([s])
    return free


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


def _names_target(names, ctx):
    """Single name -> bare Name node; several -> Tuple (keeps 1-output
    control flow a pytree LEAF end to end, which the autograd tape's
    single-output cotangent path requires)."""
    if len(names) == 1:
        return ast.Name(id=names[0], ctx=ctx())
    return _names_tuple(names, ctx)


class _ControlFlowTransformer:
    """Statement-ordered rewriter: walking each block in order tracks which
    names are BOUND before a given if/while, which decides both the
    call-site inputs (must be bound) and the outputs (a name assigned in
    only one branch is an output only if it was bound before — the other
    branch then passes the incoming value through; a one-sided NEW name
    stays branch-local, same as the reference's UndefinedVar stance)."""

    def __init__(self, local_names: set):
        self.locals = set(local_names)
        self.n = 0

    def transform_function(self, fdef):
        fdef.body = self._block(fdef.body, _param_names(fdef.args))
        return fdef

    def _block(self, stmts, bound, rest=frozenset()):
        """``bound`` tracks names DEFINITELY bound on every path — a
        conditional assignment must not license an unconditional call-site
        load further down."""
        out = []
        for i, s in enumerate(stmts):
            # names the REST of the function may read: the tail of this
            # block plus whatever the enclosing blocks read after us
            tail_reads = _free_reads(stmts[i + 1:]) | set(rest)
            if isinstance(s, ast.If):
                new, defb = self._if(s, bound, tail_reads)
                out.extend(new)
                bound |= defb
            elif isinstance(s, ast.While):
                new, defb = self._while(s, bound, tail_reads)
                out.extend(new)
                bound |= defb
            elif isinstance(s, ast.For):
                new, defb = self._for_range(s, bound, tail_reads)
                out.extend(new)
                bound |= defb
            elif isinstance(s, ast.With):
                # loop bodies re-read their own names across iterations —
                # count the whole statement's loads as "later reads"
                sub_rest = tail_reads | _loaded_names(s)
                s.body = self._block(s.body, set(bound), sub_rest)
                if getattr(s, "orelse", None):
                    s.orelse = self._block(s.orelse, set(bound), sub_rest)
                out.append(s)
                bound |= _definitely_bound([s])
            else:
                out.append(s)
                bound |= _assigned_names([s])
        return out

    # -- if/elif/else -------------------------------------------------------
    def _if(self, node: ast.If, bound, rest=frozenset()):
        node.body = self._block(node.body, set(bound), rest)
        node.orelse = self._block(node.orelse, set(bound), rest)
        branches = node.body + node.orelse
        if _has_jump(branches) or _has_object_store(branches):
            return [node], _definitely_bound([node])
        a_t = _assigned_names(node.body) & self.locals
        a_f = _assigned_names(node.orelse) & self.locals
        # outputs: assigned on both paths, or assigned on one path with a
        # pre-bound value flowing through the other
        outs = sorted((a_t & a_f) | ((a_t | a_f) & bound))
        if not outs:
            return [node], _definitely_bound([node])
        # a one-sided NEW name (no pre-bound value, not assigned on the
        # other path) becomes branch-local in the rewrite. That is fine for
        # genuine temporaries, but if anything LATER reads the name the
        # rewrite would silently drop a live binding — leave the if
        # untouched instead (python-bool branches keep exact eager
        # semantics, tensor predicates fail loudly at trace)
        if ((a_t | a_f) - set(outs)) & set(rest):
            return [node], _definitely_bound([node])
        reads = (_free_reads(node.body) | _free_reads(node.orelse)
                 | _loaded_names(node.test))
        ins = sorted(((reads | set(outs)) & self.locals & bound))
        i = self.n
        self.n += 1

        def mk_branch(name, body):
            body = list(body) or [ast.Pass()]
            body.append(ast.Return(value=_names_target(outs, ast.Load)))
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(
                    posonlyargs=[], args=[ast.arg(arg=a) for a in ins],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=body, decorator_list=[], type_params=[])

        t_name, f_name = f"__pt_true_{i}", f"__pt_false_{i}"
        call = ast.Assign(
            targets=[_names_target(outs, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="__pt_jst", ctx=ast.Load()),
                    attr="convert_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=t_name, ctx=ast.Load()),
                      ast.Name(id=f_name, ctx=ast.Load()),
                      _names_tuple(ins, ast.Load)],
                keywords=[]))
        # the call site assigns every out unconditionally
        return ([mk_branch(t_name, node.body),
                 mk_branch(f_name, node.orelse), call], set(outs))

    # -- counted for --------------------------------------------------------
    def _for_range(self, node: ast.For, bound, rest=frozenset()):
        """``for i in range(...)`` with a clean body -> convert_range_for
        (jit.scan when the carry holds tensors: one trace instead of
        trip-count unrolls; see the runtime helper for the fallbacks).
        Anything else keeps python semantics (recursed body only)."""
        sub_rest = set(rest) | _loaded_names(node)
        node.body = self._block(node.body, set(bound), sub_rest)
        if node.orelse:
            node.orelse = self._block(node.orelse, set(bound), sub_rest)

        def keep():
            return [node], _definitely_bound([node])

        if (node.orelse or _has_jump(node.body)
                or _has_object_store(node.body)
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and not node.iter.keywords)):
            return keep()
        tname = node.target.id
        assigned = _assigned_names(node.body) & self.locals
        loop = sorted((assigned - {tname}) & bound)
        if not loop:
            return keep()
        # the rewrite drops body-new names and the final index binding —
        # bail if anything later reads them (same stance as _while)
        if ((assigned - set(loop) - {tname}) | {tname}) & set(rest):
            return keep()
        i = self.n
        self.n += 1
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=tname)] + [ast.arg(arg=a) for a in loop],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        body_def = ast.FunctionDef(
            name=f"__pt_forbody_{i}", args=args,
            body=list(node.body) + [
                ast.Return(value=_names_tuple(loop, ast.Load))],
            decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[_names_tuple(loop, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="__pt_jst", ctx=ast.Load()),
                    attr="convert_range_for", ctx=ast.Load()),
                args=[ast.Tuple(elts=list(node.iter.args), ctx=ast.Load()),
                      ast.Name(id=f"__pt_forbody_{i}", ctx=ast.Load()),
                      _names_tuple(loop, ast.Load)],
                keywords=[]))
        return [body_def, call], set(loop)

    # -- while --------------------------------------------------------------
    def _while(self, node: ast.While, bound, rest=frozenset()):
        node.body = self._block(node.body, set(bound),
                                set(rest) | _loaded_names(node))
        if node.orelse or _has_jump(node.body) or \
                _has_object_store(node.body):
            return [node], set()
        # carry = mutated names with a pre-loop value (lax.while_loop needs
        # an initial carry; body temporaries stay local to the body fn)
        assigned = _assigned_names(node.body) & self.locals
        loop = sorted(assigned & bound)
        if not loop:
            return [node], set()
        # a body-new name read later would be dropped by the rewrite
        if (assigned - set(loop)) & set(rest):
            return [node], set()
        i = self.n
        self.n += 1
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=a) for a in loop],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_def = ast.FunctionDef(
            name=f"__pt_cond_{i}", args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            type_params=[])
        body_def = ast.FunctionDef(
            name=f"__pt_body_{i}", args=args,
            body=list(node.body) + [
                ast.Return(value=_names_tuple(loop, ast.Load))],
            decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[_names_tuple(loop, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="__pt_jst", ctx=ast.Load()),
                    attr="convert_while", ctx=ast.Load()),
                args=[ast.Name(id=f"__pt_cond_{i}", ctx=ast.Load()),
                      ast.Name(id=f"__pt_body_{i}", ctx=ast.Load()),
                      _names_tuple(loop, ast.Load)],
                keywords=[]))
        return [cond_def, body_def, call], set(loop)


def ast_transform(fn: Callable) -> Callable:
    """Rewrite ``fn``'s tensor-dependent if/while into ``convert_*`` calls;
    returns the rebuilt function (closure values captured at transform
    time)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise Dy2StaticError(f"dy2static: source unavailable for "
                             f"{fn!r} ({e})") from None
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise Dy2StaticError("dy2static: expected a function definition")
    fdef.decorator_list = []

    local_names = _param_names(fdef.args) | _assigned_names(fdef.body)

    new_fdef = _ControlFlowTransformer(local_names).transform_function(fdef)
    ast.fix_missing_locations(new_fdef)

    # rebuild inside a factory taking the original closure's freevars
    free = fn.__code__.co_freevars
    factory = ast.FunctionDef(
        name="__pt_factory",
        args=ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in free],
            kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=[new_fdef,
              ast.Return(value=ast.Name(id=new_fdef.name, ctx=ast.Load()))],
        decorator_list=[], type_params=[])
    module = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(module)

    glb = dict(fn.__globals__)
    import paddle_tpu.jit.dy2static as _jst_mod
    glb["__pt_jst"] = _jst_mod
    code = compile(module, filename=f"<dy2static {fn.__name__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)
    cells = [c.cell_contents for c in (fn.__closure__ or ())]
    new_fn = ns["__pt_factory"](*cells)
    functools.wraps(fn)(new_fn)
    return new_fn
