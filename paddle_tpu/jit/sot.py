"""SOT — the bytecode-tier dynamic-to-static capture (guards, graph breaks,
path-specialized compilation).

Parity target: the reference's ``python/paddle/jit/sot/`` ("Symbolic Opcode
Translator": a CPython-bytecode interpreting tracer with guard-based graph
capture and graph-break fallback — the torchdynamo equivalent; SURVEY §2.4).

TPU redesign, not a translation. The reference must interpret bytecode
frame-by-frame because its eager ops execute immediately and can only be
intercepted by owning the interpreter loop. Here every tensor op already
funnels through ONE dispatcher (``core.dispatch.forward_op``) and every
tensor->Python materialization goes through four dunders — so the same
capture semantics fall out of two far smaller mechanisms:

* **Materialization events** (the graph-break points): ``bool(t)`` /
  ``int(t)`` / ``float(t)`` / ``t.item()`` on a traced tensor are exactly
  the places the reference's opcode translator breaks the graph
  (``POP_JUMP_IF_*`` on a tensor, scalar extraction). A hook on those
  dunders records each event's concrete outcome during an eager CAPTURE run,
  and replays the recorded outcome during the compile trace — so the trace
  proceeds through data-dependent ``if``/``while``/``for`` (including
  ``return`` inside a branch) along the OBSERVED path, and the event tensors
  become extra program outputs whose runtime values VALIDATE the path.
* **Guards**: (a) the input signature (pytree structure, tensor
  shapes/dtypes, non-tensor argument values); (b) a CPython-bytecode scan
  (``dis``) of the function's code object — recursing into nested code
  constants — collecting every ``LOAD_GLOBAL``/``LOAD_DEREF`` name whose
  current value is a guardable scalar, snapshotted at capture and checked
  per call (closure-const guards); (c) the per-path event outcomes, checked
  against the compiled program's own event outputs after each run.
* **Path specialization** (the resume-function equivalent): each distinct
  control-flow path through the tensor-dependent branches compiles to its
  own full program. A run whose event outputs diverge from the path's
  recorded outcomes is rolled back (state snapshot/restore around the call
  — programs are functionalized, so commit is a Python-side writeback) and
  re-dispatched to the matching path, or re-captured eagerly. The path
  table is capped; overflow (e.g. a ``float(loss)`` that changes every
  step) degrades to permanent eager execution with one warning — the
  graph-break-with-eager-fallback contract.

What this tier adds over the AST tier (``jit/dy2static.py``): branches
containing ``return``/``break``/``continue``, attribute/object stores,
data-dependent ``for``/``while`` (specialized per trip count), and
gradients through data-dependent control flow (the branch is resolved at
trace time, so backward compiles through the taken path — the AST tier's
``while`` refusal does not apply here).

Semantics contract (same as ``to_static`` generally): Python side effects
(prints, attribute/global/item stores) run during capture and are NOT
replayed by compiled calls — a bytecode scan DETECTS store ops up front
and warns once (r5); ``.numpy()``/``.tolist()`` inside the compiled
region are a hard graph break (permanent eager fallback for that
signature). Guards cover scalar AND small container/ndarray
globals/closures by content digest, so mutating a guarded list/dict/array
recompiles instead of serving a stale path (r5). The first call captures
and compiles — there is no warmup-eager call (r5).
"""

from __future__ import annotations

import dis
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core import tensor as _tensor_mod
from ..core.tensor import Tensor, _wrap_value
from .trace import CompiledProgram

__all__ = ["SOTFunction", "sot_capture_active", "GuardedEntry"]

_MAX_PATHS = 8          # live per-signature path-table cap (LRU-evicted)
_MAX_CHURN = 32         # total compiles per entry before eager demotion
_MISSING = object()


# ---------------------------------------------------------------------------
# materialization-event hook (installed into core.tensor dunders)
# ---------------------------------------------------------------------------

class _EventCtx:
    """Active while a SOT capture (eager) or replay (compile trace) runs."""

    def __init__(self, mode: str, recorded: Optional[List] = None):
        assert mode in ("capture", "replay")
        self.mode = mode
        self.outcomes: List[Tuple[str, Any]] = []   # capture: recorded here
        self.recorded = recorded or []              # replay: fed from here
        self.cursor = 0
        self.event_vals: List[Any] = []             # replay: event tracers

    def on_event(self, kind: str, t: Tensor):
        if self.mode == "capture":
            val = {"bool": lambda v: bool(v), "int": lambda v: int(v),
                   "float": lambda v: float(v),
                   "item": lambda v: v.item()}[kind](t._value)
            self.outcomes.append((kind, val))
            return val
        # replay: the tensor value may be a tracer — record it as an extra
        # program output and return the recorded concrete outcome so Python
        # control flow proceeds along the captured path
        if self.cursor >= len(self.recorded):
            raise _PathDiverged(
                f"extra materialization event #{self.cursor} ({kind}) during "
                f"replay — the function is not deterministic given its guards")
        rk, rv = self.recorded[self.cursor]
        if rk != kind:
            raise _PathDiverged(
                f"event #{self.cursor} kind changed ({rk} -> {kind})")
        self.cursor += 1
        self.event_vals.append(jnp.asarray(t._value))
        return rv


class _PathDiverged(RuntimeError):
    pass


def sot_capture_active() -> bool:
    return _tensor_mod._materialize_hook is not None


class _hook_installed:
    def __init__(self, ctx: _EventCtx):
        self.ctx = ctx

    def __enter__(self):
        self.prev = _tensor_mod._materialize_hook
        _tensor_mod._materialize_hook = self.ctx.on_event
        return self.ctx

    def __exit__(self, *exc):
        _tensor_mod._materialize_hook = self.prev
        return False


# ---------------------------------------------------------------------------
# guards
# ---------------------------------------------------------------------------

def _guardable(v) -> bool:
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return True
    if isinstance(v, (tuple, list)) and len(v) <= 8:
        return all(_guardable(x) for x in v)
    if isinstance(v, dict) and len(v) <= 8:
        return all(isinstance(k, (str, int)) and _guardable(x)
                   for k, x in v.items())
    if isinstance(v, np.ndarray) and v.size <= 64:
        return True
    return False


def _guard_digest(v):
    """Canonical, content-based snapshot value: mutating a guarded list /
    dict / small ndarray closure INVALIDATES the entry (recompile) instead
    of silently serving a stale path (r4 VERDICT weak #6)."""
    if isinstance(v, (tuple, list)):
        return ("seq", type(v).__name__,
                tuple(_guard_digest(x) for x in v))
    if isinstance(v, dict):
        # keys may mix int/str (both admitted by _guardable): sort by a
        # type-tagged repr so the sort never compares across types
        return ("map", tuple(sorted(
            ((type(k).__name__, repr(k), _guard_digest(x))
             for k, x in v.items()))))
    if isinstance(v, np.ndarray):
        return ("nd", v.shape, str(v.dtype), v.tobytes())
    return v


def _scan_code_reads(code) -> Tuple[set, set]:
    """Bytecode scan: every global / closure name the code object (and its
    nested code constants) reads. This is the tier's actual bytecode pass —
    the guard SOURCES the reference's opcode translator derives from
    LOAD_GLOBAL / LOAD_DEREF while interpreting."""
    globals_read, derefs_read = set(), set()
    stack = [code]
    while stack:
        c = stack.pop()
        for ins in dis.get_instructions(c):
            if ins.opname == "LOAD_GLOBAL":
                globals_read.add(ins.argval)
            elif ins.opname in ("LOAD_DEREF", "LOAD_CLASSDEREF"):
                derefs_read.add(ins.argval)
        for const in c.co_consts:
            if hasattr(const, "co_code"):
                stack.append(const)
    return globals_read, derefs_read


_SIDE_EFFECT_OPS = {"STORE_GLOBAL", "DELETE_GLOBAL", "STORE_ATTR",
                    "DELETE_ATTR", "STORE_SUBSCR", "DELETE_SUBSCR"}
# mutating METHOD calls (list.append etc.) don't emit store opcodes; the
# scan flags loads of these names as probable mutations (heuristic — a
# false positive only costs an informational warning)
_MUTATING_METHODS = {"append", "extend", "insert", "update", "setdefault",
                     "pop", "popitem", "remove", "clear", "add", "discard",
                     "write", "sort", "reverse"}


def _container_mutated_names(code) -> set:
    """Names of GLOBAL/CLOSURE variables the code mutates through
    subscript stores or mutating method calls, found by tracking the
    loaded object through a SYMBOLIC stack: a STORE_SUBSCR marks a name
    only when its container operand actually originates from that
    LOAD_GLOBAL/LOAD_DEREF (directly, or via a chained subscript/attr —
    ``cfg[i][j] = v`` and ``cfg.data[k] = v`` still count as mutating
    ``cfg``). The earlier flat 12-instruction window marked a container
    whenever ANY subscript store followed its load, so ``x = cfg[k];
    buf[i] = x`` dropped the guard on the read-only global ``cfg`` and
    external mutation of it served a stale compiled path (ADVICE r5).
    Unmodeled opcodes conservatively clear every tag: a false NEGATIVE
    only keeps a guard alive (worst case a recompile); a false positive
    would silently disable stale-path protection."""
    names = set()
    codes = [code]
    while codes:
        c = codes.pop()
        _scan_container_mutations(c, names)
        for const in c.co_consts:
            if hasattr(const, "co_code"):
                codes.append(const)
    return names


def _scan_container_mutations(c, names: set) -> None:
    sym: list = []          # one entry per stack slot: a name tag or None

    def pop(n):
        del sym[len(sym) - n:]

    for ins in dis.get_instructions(c):
        op = ins.opname
        if ins.is_jump_target:
            sym = [None] * len(sym)       # merged control flow: unknown
        if op in ("STORE_GLOBAL", "DELETE_GLOBAL"):
            names.add(ins.argval)
            if op == "STORE_GLOBAL":
                pop(1)
            continue
        if op in ("LOAD_GLOBAL", "LOAD_DEREF", "LOAD_CLASSDEREF"):
            # 3.11+ LOAD_GLOBAL may push NULL below the value (eff 2)
            try:
                eff = dis.stack_effect(ins.opcode, ins.arg)
            except ValueError:
                eff = 1
            sym.extend([None] * (eff - 1) + [ins.argval])
            continue
        if op in ("LOAD_CONST", "LOAD_FAST", "LOAD_SMALL_INT"):
            sym.append(None)
            continue
        if op in ("LOAD_ATTR", "LOAD_METHOD"):
            owner = sym[-1] if sym else None
            if owner is not None and ins.argval in _MUTATING_METHODS:
                names.add(owner)
            pop(1)
            try:
                eff = dis.stack_effect(ins.opcode, ins.arg)
            except ValueError:
                eff = 0
            # attribute access propagates the tag: mutating cfg.data
            # mutates what the digest of cfg covers
            sym.extend([None] * eff + [owner])
            continue
        if op == "BINARY_SUBSCR":
            tag = sym[-2] if len(sym) >= 2 else None
            pop(2)
            sym.append(tag)               # cfg[i] is still "part of" cfg
            continue
        if op == "BINARY_SLICE":          # 3.12+: TOS2[TOS1:TOS], pops 3
            tag = sym[-3] if len(sym) >= 3 else None
            pop(3)
            sym.append(tag)
            continue
        if op == "STORE_SLICE":           # 3.12+: TOS2[TOS1:TOS] = TOS3
            if len(sym) >= 3 and sym[-3] is not None:
                names.add(sym[-3])
            pop(4)
            continue
        if op == "STORE_SUBSCR":
            if len(sym) >= 2 and sym[-2] is not None:
                names.add(sym[-2])
            pop(3)
            continue
        if op == "DELETE_SUBSCR":
            if len(sym) >= 2 and sym[-2] is not None:
                names.add(sym[-2])
            pop(2)
            continue
        if op == "POP_TOP":
            pop(1)
            continue
        if op == "DUP_TOP":
            sym.append(sym[-1] if sym else None)
            continue
        if op == "DUP_TOP_TWO":
            sym.extend(sym[-2:] if len(sym) >= 2 else [None, None])
            continue
        if op in ("ROT_TWO", "ROT_THREE", "ROT_FOUR"):
            n = {"ROT_TWO": 2, "ROT_THREE": 3, "ROT_FOUR": 4}[op]
            if len(sym) >= n:
                sym[-n:] = [sym[-1]] + sym[-n:-1]
            continue
        if op == "COPY":                  # 3.11+
            i = ins.arg or 1
            sym.append(sym[-i] if len(sym) >= i else None)
            continue
        if op == "SWAP":                  # 3.11+
            i = ins.arg or 1
            if len(sym) >= i:
                sym[-1], sym[-i] = sym[-i], sym[-1]
            continue
        if op == "BINARY_OP" or op.startswith(("BINARY_", "INPLACE_")):
            pop(2)
            sym.append(None)              # a fresh (or consumed) value
            continue
        if op.startswith("UNARY_"):
            if sym:
                sym[-1] = None            # pop 1 push 1, tag dropped
            continue
        if op in ("STORE_FAST", "STORE_DEREF", "STORE_NAME", "STORE_ATTR"):
            try:
                eff = dis.stack_effect(ins.opcode, ins.arg)
            except ValueError:
                eff = -1
            pop(-eff)
            continue
        # anything else: keep the depth honest, drop every tag — an
        # unmodeled opcode may have rearranged the stack arbitrarily
        try:
            eff = dis.stack_effect(ins.opcode, ins.arg)
        except ValueError:
            sym = []
            continue
        sym = [None] * max(0, len(sym) + eff)


def _detect_side_effects(fn: Callable) -> Optional[str]:
    """Static bytecode scan for Python side effects (object/global/item
    stores) the compiled replay will NOT re-run (r4 VERDICT weak #6: detect
    and warn instead of silently dropping). Returns a description or
    None."""
    fn = getattr(fn, "__func__", fn)
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    hits = []
    stack = [code]
    while stack:
        c = stack.pop()
        for ins in dis.get_instructions(c):
            if ins.opname in _SIDE_EFFECT_OPS:
                hits.append(f"{ins.opname} {ins.argval}")
            elif ins.opname in ("LOAD_METHOD", "LOAD_ATTR") and \
                    ins.argval in _MUTATING_METHODS:
                hits.append(f"call .{ins.argval}()")
        for const in c.co_consts:
            if hasattr(const, "co_code"):
                stack.append(const)
    if hits:
        uniq = sorted(set(hits))[:5]
        return ", ".join(uniq)
    return None


def _code_guard_snapshot(fn: Callable) -> Dict[str, Any]:
    """name -> digest for every guardable global/closure value the
    function's bytecode reads. Container/ndarray digests are only taken
    for functions with NO store bytecodes: a function that mutates
    subscripts/attributes itself (a step counter, an appended log) would
    otherwise invalidate its own guards every call and recompile forever —
    for those, containers stay unguarded (scalars still guard) and the
    side-effect warning covers the semantics."""
    fn = getattr(fn, "__func__", fn)          # unwrap bound methods
    code = getattr(fn, "__code__", None)
    if code is None:
        return {}
    # container guards are skipped ONLY for the specific global/closure
    # names the code itself mutates (a step counter, an appended log) —
    # other container guards stay live even in functions with local
    # mutations (r5 review fix: the previous all-or-nothing switch
    # disabled stale-path protection for most real functions)
    mutated = _container_mutated_names(code)
    globals_read, derefs_read = _scan_code_reads(code)
    snap: Dict[str, Any] = {}
    g = getattr(fn, "__globals__", {})
    for name in globals_read:
        v = g.get(name, _MISSING)
        if v is _MISSING or not _guardable(v):
            continue
        if name in mutated and not isinstance(
                v, (bool, int, float, str, bytes, tuple, type(None))):
            continue
        snap[f"g:{name}"] = _guard_digest(v)
    cells = dict(zip(code.co_freevars, fn.__closure__ or ()))
    for name in derefs_read:
        cell = cells.get(name)
        if cell is not None:
            try:
                v = cell.cell_contents
            except ValueError:      # empty cell
                continue
            if not _guardable(v):
                continue
            if name in mutated and not isinstance(
                    v, (bool, int, float, str, bytes, tuple, type(None))):
                continue
            snap[f"c:{name}"] = _guard_digest(v)
    return snap




def _input_sig(args, kwargs, train_flags=()):
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    parts = []
    for l in leaves:
        if isinstance(l, Tensor):
            parts.append(("T", tuple(l.shape), str(l.dtype)))
        elif isinstance(l, (jax.Array, np.ndarray)):
            parts.append(("A", tuple(l.shape), str(l.dtype)))
        else:
            try:
                parts.append(("S", hash(l), type(l).__name__))
            except TypeError:
                parts.append(("S", repr(l)))
    return (treedef, tuple(parts), tuple(train_flags))


# ---------------------------------------------------------------------------
# per-signature entry: guards + path table
# ---------------------------------------------------------------------------

class GuardedEntry:
    def __init__(self, code_guards: Dict[str, Any]):
        self.code_guards = code_guards
        # insertion/use-ordered: the path table evicts LRU on overflow
        # (r4 VERDICT weak #6 / next #7 — overflow is no longer a permanent
        # demotion); churn beyond _MAX_CHURN total compiles demotes to
        # eager (a truly path-unstable function would thrash-compile)
        from collections import OrderedDict
        self.paths: "OrderedDict[Tuple, Any]" = OrderedDict()
        self.compile_count = 0
        self.last_path: Optional[Tuple] = None
        self.eager_only: Optional[str] = None  # reason, once broken

    def guards_pass(self, fn) -> bool:
        if not self.code_guards:
            return True
        snap = _code_guard_snapshot(fn)
        return all(snap.get(k, _MISSING) == v
                   for k, v in self.code_guards.items())


def _outcome_key(outcomes) -> Tuple:
    return tuple((k, v) for k, v in outcomes)


class SOTFunction:
    """The ``backend="sot"`` tier of ``to_static`` (reference:
    ``paddle.jit.to_static`` with SOT enabled)."""

    def __init__(self, function, input_spec=None, donate_states=False,
                 layer=None, guard_target=None):
        self._fn = function
        self._guard_fn = guard_target or function  # what the bytecode scan
        # reads (the Layer case wraps forward in a lambda; guards must come
        # from the real forward's code object)
        self._input_spec = input_spec
        self._donate = donate_states
        self._layer = layer
        self._entries: Dict[Any, List[GuardedEntry]] = {}
        self._side_effects_checked = False

    # surface parity with StaticFunction
    @property
    def _train_flags(self):
        if self._layer is None:
            return ()
        return tuple(m.training
                     for m in self._layer.sublayers(include_self=True))

    def _capture_call(self, args, kwargs):
        """Eager run recording materialization outcomes (always correct —
        this IS plain eager execution with a recorder attached)."""
        ctx = _EventCtx("capture")
        with _hook_installed(ctx):
            out = self._fn(*args, **kwargs)
        return out, ctx.outcomes

    def _compile_path(self, outcomes, args, kwargs):
        """Build the path-specialized program: the standard functionalized
        trace (CompiledProgram: state binding, backward-in-program), with
        the event hook feeding recorded outcomes and exporting each event
        tensor as an extra output for runtime path validation."""
        recorded = list(outcomes)

        def fn_with_events(*a, **k):
            ctx = _EventCtx("replay", recorded)
            with _hook_installed(ctx):
                out = self._fn(*a, **k)
            if ctx.cursor != len(recorded):
                raise _PathDiverged(
                    f"only {ctx.cursor} of {len(recorded)} events fired "
                    "during replay")
            events = tuple(_wrap_value(v, stop_gradient=True)
                           for v in ctx.event_vals)
            return (out, events)

        return CompiledProgram(fn_with_events, args, kwargs,
                               donate_states=self._donate, layer=self._layer)

    def _run_checked(self, entry: GuardedEntry, key, args, kwargs):
        """Run the path's program; validate event outputs against the
        recorded outcomes; roll back state and return None on divergence."""
        from ..ops import random as _random
        prog = entry.paths[key]
        state_saved = [t._raw for t in prog._state]
        extra_saved = [t._raw for t in prog._extra_state]
        gen = _random.default_generator()
        key_saved = gen.key
        out, events = prog(args, kwargs)
        actual = []
        ok = True
        for (kind, recv), ev in zip(key, events):
            conv = {"bool": bool, "int": int, "float": float,
                    "item": lambda v: np.asarray(v).item()}[kind]
            a = conv(np.asarray(ev._value if isinstance(ev, Tensor) else ev))
            actual.append((kind, a))
            if a != recv:
                ok = False
                break
        if ok:
            entry.last_path = key
            entry.paths.move_to_end(key)      # LRU: mark most-recent
            return True, out
        # divergence: undo the program's state writeback (programs are pure;
        # commit was the Python-side assignment we just reverse)
        for t, v in zip(prog._state, state_saved):
            t._raw = v
        for t, v in zip(prog._extra_state, extra_saved):
            t._raw = v
        gen.key = key_saved
        return False, _outcome_key(actual)   # trustworthy prefix

    def __call__(self, *args, **kwargs):
        from .api import _to_static_enabled, autograd_under_trace
        if not _to_static_enabled or autograd_under_trace() \
                or sot_capture_active():
            return self._fn(*args, **kwargs)
        # r5 (VERDICT r4 weak #6): no warmup-eager special case — the
        # FIRST call captures and compiles. Capture IS a plain eager run
        # with a recorder attached, so lazy state init (Parameter creation
        # on first forward) happens during capture exactly as it would
        # eagerly, and the compile trace that follows sees fully-built
        # state. Once-called functions therefore compile too.
        if not self._side_effects_checked:
            self._side_effects_checked = True
            se = _detect_side_effects(self._guard_fn)
            if se is not None:
                warnings.warn(
                    "to_static[sot]: Python side effects detected in the "
                    f"captured function ({se}); they execute during "
                    "capture runs only and are NOT replayed by compiled "
                    "calls (the documented capture contract)",
                    stacklevel=2)

        sig = _input_sig(args, kwargs, self._train_flags)
        entries = self._entries.setdefault(sig, [])
        entry = next((e for e in entries if e.guards_pass(self._guard_fn)),
                     None)
        if entry is None:
            # new guard set (first sight of this signature, or a
            # closure/global constant changed): capture + compile fresh
            entry = GuardedEntry(_code_guard_snapshot(self._guard_fn))
            entries.append(entry)

        if entry.eager_only is not None:
            return self._fn(*args, **kwargs)

        # fast path: try the last successful path, then any whose prefix
        # matches what we actually observe
        tried = set()
        key = entry.last_path
        while key is not None and key not in tried:
            tried.add(key)
            ok, res = self._run_checked(entry, key, args, kwargs)
            if ok:
                return res
            actual_prefix = res
            key = next(
                (k for k in entry.paths
                 if k not in tried and len(k) >= len(actual_prefix)
                 and k[:len(actual_prefix)] == actual_prefix), None)

        # no compiled path matches: eager capture (correct result), then
        # compile this path for future calls
        out, outcomes = self._capture_call(args, kwargs)
        pkey = _outcome_key(outcomes)
        if pkey not in entry.paths:
            if entry.compile_count >= _MAX_CHURN:
                entry.eager_only = (
                    f"path table churned through {_MAX_CHURN} compiles "
                    "(a materialized scalar changes every call?) — "
                    "falling back to eager execution for this signature")
                warnings.warn(f"to_static[sot]: {entry.eager_only}",
                              stacklevel=2)
                return out
            if len(entry.paths) >= _MAX_PATHS:
                # evict the least-recently-used path (front of the ordered
                # table); its program is rebuilt if that path recurs
                entry.paths.popitem(last=False)
            try:
                entry.paths[pkey] = self._compile_path(outcomes, args, kwargs)
                entry.compile_count += 1
                entry.last_path = pkey
            except Exception as e:   # graph break: permanent eager fallback
                entry.eager_only = (
                    f"graph break — path trace failed with "
                    f"{type(e).__name__}: {str(e)[:200]}")
                warnings.warn(f"to_static[sot]: {entry.eager_only}",
                              stacklevel=2)
        return out

    # paddle API compat (StaticFunction surface)
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except (OSError, TypeError):
            return "<source unavailable>"

    def rollback(self):
        return self._fn

    def concrete_program_specify_input_spec(self, *a, **k):
        return None
