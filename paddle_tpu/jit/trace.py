"""Functionalization machinery for ``paddle.jit.to_static``.

Parity target: the reference's dygraph-to-static stack (``python/paddle/jit/``:
``ProgramTranslator``/``StaticFunction`` trace-and-cache, ``PartialProgramLayer``
running a captured program inside dygraph — see SURVEY.md §3.3). TPU redesign: instead
of AST rewriting + a ProgramDesc interpreter, the imperative API is *functionalized*
onto ``jax.jit``:

1. discovery trace (``jax.make_jaxpr``) runs the python function with tracer
   arguments while the real framework state (Parameters, optimizer accumulators,
   RNG, lr) stays live; hooks on ``Tensor._value`` record every pre-existing tensor
   that is read or written — that set is the program's implicit state;
2. the compile trace binds that state as explicit inputs/outputs of a pure function
   and hands it to ``jax.jit`` — in-place mutation of parameters by
   ``optimizer.step`` becomes the state-out slot, ``loss.backward()``'s tape runs
   on tracers and is compiled into the same program.

The Paddle concepts map as: ConcreteProgram -> CompiledProgram here; program cache
keyed by input signature -> ``StaticFunction._programs``; ``run_program`` op ->
the compiled XLA executable; scope/variable transfer -> state binding below.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor, _trace_hook, _wrap_value

__all__ = ["TraceContext", "activate", "current_ctx", "CompiledProgram",
           "build_program"]


def current_ctx():
    return _trace_hook.ctx


class _Activate:
    def __init__(self, ctx):
        self.ctx = ctx
        self.prev = None

    def __enter__(self):
        self.prev = _trace_hook.ctx
        _trace_hook.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _trace_hook.ctx = self.prev
        return False


def activate(ctx):
    return _Activate(ctx)


class TraceContext:
    """Records reads/writes of pre-existing tensors while a trace runs.

    mode="discover": the first (state-discovery) trace — real state stays bound,
    reads note candidates, writes save originals for restoration.
    mode="trace": the compile trace — state is pre-bound to tracers by the caller;
    this ctx only records *extra* writes (write-only state) and RNG/host inputs.
    """

    def __init__(self, mode: str):
        assert mode in ("discover", "trace")
        self.mode = mode
        self.created: set = set()
        self.created_refs: List[Any] = []
        self.reads: "OrderedDict[int, Any]" = OrderedDict()    # id -> weakref
        self.writes: "OrderedDict[int, Any]" = OrderedDict()   # id -> weakref
        self.saved_values: Dict[int, Any] = {}
        self.saved_grads: Dict[int, Any] = {}
        self.host_inputs: "OrderedDict[Any, Callable]" = OrderedDict()
        self.host_tracers: Dict[Any, Any] = {}
        self.rng_used = False
        self.rng_counter = 0
        self.rng_tracer = None
        self.state_ids: set = set()   # trace mode: ids of pre-bound state tensors

    # -- Tensor hooks (called from core.tensor property accessors) ----------
    def note_create(self, t):
        self.created.add(id(t))
        self.created_refs.append(weakref.ref(t))

    def note_read(self, t):
        i = id(t)
        if i in self.created or i in self.reads or i in self.state_ids:
            return
        self.reads[i] = weakref.ref(t)
        self.saved_grads.setdefault(i, t.grad)

    def note_write(self, t, new_value):
        i = id(t)
        if i in self.created or i in self.state_ids:
            return  # state binding/restoration is the caller's job in trace mode
        if i not in self.saved_values:
            self.saved_values[i] = t._raw
            self.saved_grads.setdefault(i, t.grad)
        self.writes[i] = weakref.ref(t)

    # -- host-scalar inputs (e.g. the optimizer's current lr) ---------------
    def host_scalar(self, tag, provider: Callable[[], float]):
        if self.mode == "discover":
            self.host_inputs[tag] = provider
            return provider()
        tr = self.host_tracers.get(tag)
        if tr is None:
            # not seen during discovery: bake the current value as a constant
            return provider()
        return tr

    # -- RNG --------------------------------------------------------------
    def rng_key(self):
        self.rng_used = True
        if self.mode == "discover":
            from ..ops import random as _random
            return _random.default_generator().next_key()
        self.rng_counter += 1
        return jax.random.fold_in(self.rng_tracer, self.rng_counter)

    # -- restoration --------------------------------------------------------
    def restore(self):
        for i, val in self.saved_values.items():
            ref = self.writes.get(i) or self.reads.get(i)
            t = ref() if ref is not None else None
            if t is not None:
                t._raw = val
        # undo tracer grads attached by a backward() inside the trace
        for i, g0 in self.saved_grads.items():
            ref = self.writes.get(i) or self.reads.get(i)
            t = ref() if ref is not None else None
            if t is not None:
                t.grad = g0


def _check_no_escaped_tracers(ctx):
    """Tensors *created* during a trace that are still alive with tracer values
    were stored into long-lived objects (e.g. lazily-initialized optimizer
    accumulators) — state the functionalization can't transport. One eager
    warmup call creates such state with real values (StaticFunction does this)."""
    import gc

    gc.collect()
    escaped = []
    for ref in ctx.created_refs:
        t = ref()
        if t is not None and isinstance(t._raw, jax.core.Tracer):
            escaped.append(t.name)
    if escaped:
        raise RuntimeError(
            "to_static: state was lazily created during tracing and escaped the "
            f"trace ({escaped[:5]}...). Run the function eagerly once before "
            "compiling (StaticFunction's first call does this automatically).")


class CompiledProgram:
    """One compiled (signature-specialized) program: the XLA executable plus the
    state-binding plan (Paddle ConcreteProgram + run_program equivalent)."""

    def __init__(self, fn, example_args, example_kwargs, donate_states=False,
                 layer=None):
        self._fn = fn
        self._donate = donate_states
        self._layer = layer
        self._build(example_args, example_kwargs)

    # -- build --------------------------------------------------------------
    def _build(self, args, kwargs):
        from ..ops import random as _random

        leaves, self._in_tree = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        self._tensor_pos = [i for i, l in enumerate(leaves)
                            if isinstance(l, Tensor)]
        self._static_leaves = [None if isinstance(l, Tensor) else l for l in leaves]
        self._arg_meta = [(bool(leaves[i].stop_gradient), leaves[i].name)
                          for i in self._tensor_pos]
        example_vals = [leaves[i]._raw for i in self._tensor_pos]

        # ---- pass 1: state discovery --------------------------------------
        gen = _random.default_generator()
        saved_key = gen.key
        ctx = TraceContext("discover")

        def discover(*arr_ins):
            with activate(ctx):
                call_args, call_kwargs = self._rebuild(arr_ins)
                self._fn(*call_args, **call_kwargs)
            return 0

        try:
            jax.make_jaxpr(discover)(*example_vals)
        finally:
            ctx.restore()
            gen.key = saved_key
        _check_no_escaped_tracers(ctx)

        state: List[Tensor] = []
        seen = set()
        for store in (ctx.reads, ctx.writes):
            for i, ref in store.items():
                t = ref()
                if t is not None and i not in seen:
                    seen.add(i)
                    state.append(t)
        self._state = state
        self._host_tags = list(ctx.host_inputs.keys())
        self._host_providers = list(ctx.host_inputs.values())
        self._rng_used = ctx.rng_used

        # ---- pass 2: compile ----------------------------------------------
        # structure discovered during the jit trace, captured via these cells
        self._out_tree = None
        self._out_is_tensor: List[bool] = []
        self._extra_state: List[Tensor] = []
        self._grad_slots: List[int] = []
        state_list = self._state

        def pure_fn(arr_ins, state_vals, host_vals, rng_key):
            ctx2 = TraceContext("trace")
            ctx2.host_tracers = dict(zip(self._host_tags, host_vals))
            ctx2.rng_tracer = rng_key
            ctx2.state_ids = {id(t) for t in state_list}
            saved = [(t._raw, t.grad, t._grad_node, t._node_index)
                     for t in state_list]
            for t, v in zip(state_list, state_vals):
                t._raw = v
                t.grad = None
                t._grad_node = None
                t._node_index = 0
            try:
                with activate(ctx2):
                    call_args, call_kwargs = self._rebuild(arr_ins)
                    out = self._fn(*call_args, **call_kwargs)
                out_leaves, out_tree = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                self._out_tree = out_tree
                self._out_is_tensor = [isinstance(l, Tensor) for l in out_leaves]
                out_vals = [l._raw if isinstance(l, Tensor) else l
                            for l in out_leaves]
                new_state = [t._raw for t in state_list]
                extra = []
                extra_vals = []
                for i, ref in ctx2.writes.items():
                    t = ref()
                    if t is not None and i not in ctx2.state_ids:
                        extra.append(t)
                        extra_vals.append(t._raw)
                self._extra_state = extra
                self._grad_slots = [k for k, t in enumerate(state_list)
                                    if t.grad is not None]
                grad_vals = [state_list[k].grad._raw for k in self._grad_slots]
                return out_vals, new_state, extra_vals, grad_vals
            finally:
                for t, (v, g, n, ix) in zip(state_list, saved):
                    t._raw = v
                    t.grad = g
                    t._grad_node = n
                    t._node_index = ix
                ctx2.restore()

        donate = (1,) if self._donate else ()
        self._compiled = jax.jit(pure_fn, donate_argnums=donate)
        # Trace now (aot) so the structure cells are filled before first use.
        self._lowered = None

    def _rebuild(self, arr_ins):
        leaves = list(self._static_leaves)
        for pos, v, (sg, name) in zip(self._tensor_pos, arr_ins, self._arg_meta):
            t = _wrap_value(v, stop_gradient=sg)
            t.name = name
            leaves[pos] = t
        return jax.tree_util.tree_unflatten(self._in_tree, leaves)

    # -- run ----------------------------------------------------------------
    def __call__(self, args, kwargs):
        from ..ops import random as _random

        leaves, _ = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        arr_ins = [leaves[i]._raw for i in self._tensor_pos]
        state_vals = [t._raw for t in self._state]
        host_vals = [jnp.asarray(p(), jnp.float32) for p in self._host_providers]
        rng = (_random.default_generator().next_key() if self._rng_used
               else jnp.zeros((2,), jnp.uint32))
        out_vals, new_state, extra_vals, grad_vals = self._compiled(
            arr_ins, state_vals, host_vals, rng)
        for t, v in zip(self._state, new_state):
            t._raw = v
            t._version += 1
        for t, v in zip(self._extra_state, extra_vals):
            t._raw = v
            t._version += 1
        for k, v in zip(self._grad_slots, grad_vals):
            self._state[k].grad = _wrap_value(v)
        out_leaves = []
        for is_t, v in zip(self._out_is_tensor, out_vals):
            out_leaves.append(_wrap_value(v) if is_t else v)
        return jax.tree_util.tree_unflatten(self._out_tree, out_leaves)


def build_program(fn, args, kwargs, donate_states=False, layer=None):
    prog = CompiledProgram(fn, args, kwargs, donate_states=donate_states,
                           layer=layer)
    return prog
