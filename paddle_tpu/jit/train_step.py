"""Fused donation-aware train step.

Parity target: the reference's fused training executors (the static-graph
``ParallelExecutor``/``StandaloneExecutor`` train loop, where forward,
backward and the optimizer update are one Program run end-to-end by C++)
and its ``paddle.incubate`` fused optimizer paths. TPU redesign: the
imperative ``loss.backward(); opt.step()`` sequence is functionalized onto
ONE ``jax.jit`` program via the to_static machinery (jit/trace.py) with the
program's state argument — parameters, optimizer accumulators, BatchNorm
running stats — **donated** to XLA (``donate_argnums``). Donation lets XLA
write updated parameters into the buffers the old parameters occupied, which

* halves the HBM working set of the update (no live old+new copy), and
* removes the per-step Python dispatch of every layer/op — the host issues
  one executable per step.

Degradation contract (tier-1 / CPU): XLA on CPU ignores donation and warns
per dispatch, so donation auto-disables off-TPU (``donation_supported``);
everything still runs, just undonated. Donation never changes numerics —
it is purely a buffer-aliasing contract — which the donation parity test
(tests/test_train_step.py) pins: K donated fused steps must produce results
identical to the eager tape path.

After a donated step the previous parameter buffers are dead; the framework
rebinds every state Tensor to the program's outputs (CompiledProgram), so
user-visible Tensors stay valid — only raw ``jax.Array`` references captured
*before* the step are invalidated (the standard jax donation contract).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Optional, Sequence

import jax

from ..core.tensor import Tensor, to_tensor
from .api import StaticFunction

__all__ = ["TrainStep", "make_train_step", "jit_step", "donation_supported"]


def donation_supported(backend: Optional[str] = None) -> bool:
    """True when the backend actually implements input/output buffer
    aliasing (TPU/GPU). CPU ignores donation and emits a per-dispatch
    warning — the fused step auto-disables donation there."""
    b = backend if backend is not None else jax.default_backend()
    return b not in ("cpu",)


def jit_step(fn: Callable, donate_argnums: Sequence[int] = (),
             static_argnums: Sequence[int] = (), annotation: str = "step"):
    """``jax.jit`` for functional train steps, with the perf-layer contract:

    * ``donate_argnums`` is applied only where the backend supports donation
      (CPU would warn on every dispatch and do nothing),
    * each dispatch runs under an ``annotate(annotation)`` profiling span
      (no-op unless ``FLAGS_profile_annotations``).

    Used by bench.py's llama/tuned/checkpoint sections; the raw jitted
    callable is available as ``wrapped._jitted``.
    """
    donate = tuple(donate_argnums) if donation_supported() else ()
    jfn = jax.jit(fn, donate_argnums=donate,
                  static_argnums=tuple(static_argnums))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        from ..profiler import annotate
        with annotate(annotation):
            return jfn(*args, **kwargs)

    wrapped._jitted = jfn
    wrapped._donate_argnums = donate
    return wrapped


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _sum_losses(loss):
    if isinstance(loss, (list, tuple)):
        total = loss[0]
        for l in loss[1:]:
            total = total + l
        return total
    return loss


class TrainStep:
    """One fused program per input signature: forward + loss + backward +
    optimizer update (+ BN running-stat updates) with donated state.

    ``step(inputs, labels)`` returns the loss Tensor (or ``(loss, outputs)``
    with ``return_outputs=True`` — hapi needs outputs for metrics). The
    first call per function runs eagerly (lazy state — optimizer
    accumulators, lazily-built sublayers — initializes with real values,
    exactly like ``to_static``); later calls hit the compiled donated
    program.

    ``scaler``: a GradScaler with dynamic loss scaling branches on
    ``isfinite`` host-side, which cannot live inside one compiled program —
    when an enabled scaler is passed the step runs on the eager tape path
    instead (documented divergence; bf16 AMP on TPU needs no loss scaling,
    which is the fused path's target).

    ``sentinel``: the run-health NaN/Inf/loss-spike detector
    (health.sentinel), fused INTO the step: the mutable state (params,
    optimizer accumulators, master weights, BN running stats) is
    snapshotted before the update and ``jnp.where``-gated after it, so a
    bad step is a state no-op — the same skip-step semantics GradScaler
    applies on found_inf, decided on device with no extra host sync.
    ``True`` builds a Sentinel from the FLAGS_health_* defaults, or pass a
    configured ``health.Sentinel``; ``None`` follows
    ``FLAGS_health_sentinel``. The verdict is readable after each step via
    ``step.sentinel.last_record()`` (one fetch of the packed health
    vector).
    """

    def __init__(self, model, optimizer, loss_fn: Callable, *,
                 amp: bool = False, amp_level: str = "O1",
                 amp_dtype: str = "bfloat16", scaler=None,
                 donate: Optional[bool] = None,
                 return_outputs: bool = False, sentinel=None):
        from ..nn.layer import Layer

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._amp = bool(amp)
        self._amp_level = amp_level
        self._amp_dtype = amp_dtype
        self._scaler = scaler
        self._return_outputs = bool(return_outputs)
        self.donate = donation_supported() if donate is None else bool(donate)
        self._eager_only = scaler is not None and scaler.is_enable()
        if sentinel is None:
            from ..flags import flag
            sentinel = bool(flag("FLAGS_health_sentinel"))
        if sentinel is True:
            from ..health.sentinel import Sentinel
            sentinel = Sentinel()
        self.sentinel = sentinel or None

        def _fn(ins, labs):
            from .. import amp as amp_mod
            if self.sentinel is not None:
                # snapshot BEFORE forward: BN running stats mutate in the
                # forward pass and must also survive a skipped step
                from ..health.sentinel import health_state_tensors
                snap = self.sentinel.snapshot(
                    health_state_tensors(self.model, self.optimizer))
            cm = (amp_mod.auto_cast(level=self._amp_level,
                                    dtype=self._amp_dtype)
                  if self._amp else contextlib.nullcontext())
            with cm:
                out = self.model(*ins)
                outs = list(out) if isinstance(out, (list, tuple)) else [out]
                loss = _sum_losses(self.loss_fn(*outs, *labs))
            if self._scaler is not None and self._scaler.is_enable():
                self._scaler.scale(loss).backward()
                self._scaler.step(self.optimizer)
                self._scaler.update()
            else:
                loss.backward()
                self.optimizer.step()
            if self.sentinel is not None:
                # re-enumerate: accumulators/masters created BY this step
                # (first call) roll back to their unborn state
                self.sentinel.gate(snap, loss, health_state_tensors(
                    self.model, self.optimizer))
            self.optimizer.clear_grad()
            return (loss, out) if self._return_outputs else loss

        self._fn = _fn
        self._sf = None if self._eager_only else StaticFunction(
            _fn, donate_states=self.donate,
            layer=model if isinstance(model, Layer) else None)

    def __call__(self, inputs, labels=()):
        ins = [t if isinstance(t, Tensor) else to_tensor(t)
               for t in _as_list(inputs)]
        labs = [t if isinstance(t, Tensor) else to_tensor(t)
                for t in _as_list(labels)]
        self.model.train()
        from ..health import watchdog
        from ..profiler import annotate
        watchdog.touch()   # progress tick for the hang watchdog (free when off)
        with annotate("step"):
            if self._sf is None:
                return self._fn(ins, labs)
            return self._sf(ins, labs)


def make_train_step(model, optimizer, loss_fn: Callable,
                    **kwargs) -> TrainStep:
    """Build a fused donation-aware train step over an imperative model.

        step = make_train_step(net, opt, nn.CrossEntropyLoss(), amp=True)
        for x, y in prefetch_to_device(loader):
            loss = step(x, y)

    See :class:`TrainStep` for the amp/scaler/donate knobs. hapi's
    ``Model.prepare(..., jit=True)`` and bench.py's resnet/detect sections
    ride this path; ``Optimizer.fuse`` is the optimizer-side spelling.
    """
    return TrainStep(model, optimizer, loss_fn, **kwargs)
