"""paddle_tpu.kernels — Pallas TPU kernels for the hot ops.

Parity target: the reference's fused kernel library
(``paddle/phi/kernels/fusion/``: flash_attn, fused_rms_norm, fused_rope; see
SURVEY.md §2.1 "Fused kernels"). Everything here operates on raw jax arrays; the
``nn.functional`` layer wraps them for Tensors and falls back to pure-jax
references where shapes/backends don't qualify. Kernels run in Pallas interpret
mode automatically off-TPU so the same code is testable on the CPU mesh;
the ONE backend/flag/interpret gate every kernel (and every caller choosing
between a kernel and its XLA fallback) resolves through is
:mod:`~paddle_tpu.kernels.dispatch` (``use_pallas``/``interpret``/``on_tpu``).
"""

from . import flash_attention as flash_attention_mod
from .dispatch import interpret, on_tpu, use_pallas
from .flash_attention import flash_attention, flash_attention_with_lse
from .paged_attention import paged_attention
from .rms_norm import rms_norm
from .rope import apply_rope, rope_cos_sin

__all__ = ["flash_attention", "flash_attention_with_lse", "rms_norm",
           "apply_rope", "rope_cos_sin", "paged_attention", "use_pallas",
           "interpret", "on_tpu"]
