"""One backend gate for every Pallas kernel in :mod:`paddle_tpu.kernels`.

Before this module each kernel file carried its own copy of the backend
check (a private ``_interpret()``), and the serving/model layers re-derived
``jax.default_backend() == "tpu"`` wherever they chose between a kernel and
its XLA fallback. Those copies could — and did — drift. This is now the ONE
place the platform / flag / interpret-mode resolution lives:

* :func:`interpret` — whether ``pl.pallas_call`` should run in interpret
  mode: kernels compile natively on TPU and run interpreted everywhere else,
  so tier-1 (CPU) exercises the REAL kernel code paths.
* :func:`on_tpu` — the raw platform predicate, for callers that pick an
  entirely different implementation off-TPU (e.g. the weight-only matmul's
  XLA dequant fallback).
* :func:`use_pallas` — resolve an on/off/auto knob (a ``FLAGS_*`` value or
  config field) to a kernel-dispatch decision. ``"auto"`` means "kernel on
  TPU, fallback elsewhere"; ``True``/``"on"`` forces the kernel (interpret
  mode off-TPU — how tests pin the kernel path on CPU); ``False``/``None``/
  ``"off"`` forces the fallback.
"""

from __future__ import annotations

from typing import Any

import jax

__all__ = ["on_tpu", "interpret", "use_pallas"]

_ON = (True, 1, "on", "1", "true", "yes")
_OFF = (None, False, 0, "off", "0", "false", "no", "none", "")


def on_tpu() -> bool:
    """Whether the default jax backend is a TPU."""
    return jax.default_backend() == "tpu"


def interpret() -> bool:
    """Pallas interpret-mode switch: compile natively on TPU, interpret
    elsewhere (same kernel code, testable on the CPU mesh)."""
    return not on_tpu()


def use_pallas(knob: Any = "auto") -> bool:
    """Resolve a kernel on/off/auto knob to a dispatch decision.

    ``True``/``"on"`` -> run the Pallas kernel (interpret mode off-TPU);
    ``False``/``None``/``"off"``/``""`` -> run the XLA fallback;
    ``"auto"`` -> kernel on TPU, fallback elsewhere. Unknown values raise
    a structured error naming the options.
    """
    k = knob.strip().lower() if isinstance(knob, str) else knob
    if isinstance(k, str):
        if k == "auto":
            return on_tpu()
        if k in _ON:
            return True
        if k in _OFF:
            return False
    elif k in (True, False, None) or isinstance(k, int):
        return bool(k)
    raise ValueError(f"unknown kernel-dispatch knob {knob!r}; options: "
                     f"True/'on', False/'off'/None, 'auto'")
