"""Pallas TPU flash attention (forward + backward).

Parity target: the reference's fused attention stack —
``paddle/phi/kernels/gpu/flash_attn_kernel.cu`` (FlashAttention-2 wrapper around
``third_party/flashattn``) and the cutlass memory-efficient fallback. TPU redesign:
a Mosaic/Pallas kernel with the online-softmax streaming algorithm, kv blocks on the
innermost grid dimension (accumulators in VMEM scratch), bf16-friendly, causal and
grouped-query (GQA) support, O(S) memory. The backward pass recomputes attention
blockwise from the saved logsumexp (no S×S materialization), matching the
flash-attention-2 recipe.

Layout: paddle's [batch, seq, heads, head_dim]; internally [B, H, S, D].
Interpret mode (CPU testing) is selected automatically off the backend.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
try:  # pltpu imports fail on non-TPU builds only at kernel-feature use time
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .dispatch import interpret as _interpret

__all__ = ["flash_attention", "flash_attention_with_lse"]

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _seg_overlap(sq_ref, sk_ref):
    """Whether this [block_q, block_k] tile can contain ANY same-segment
    pair: the segment-id RANGES of the two tiles must intersect. Sound for
    arbitrary segment ids (range test is conservative); for the packed
    layout (ids non-decreasing along the sequence — the varlen contract)
    it is exact, and skipping the disjoint tiles makes the kernel's work
    scale with the number of same-segment blocks rather than S^2 — the
    splash/sparse-causal structure of the reference's varlen kernels."""
    sq = sq_ref[0, :, 0]
    sk = sk_ref[0, :, 0]
    return (jnp.min(sq) <= jnp.max(sk)) & (jnp.min(sk) <= jnp.max(sq))


def _gate(pred_static, sq_ref, sk_ref, use_seg, run):
    """Combine the causal block gate (None = always run) with the segment
    block-skip predicate and execute ``run`` under it."""
    pred = pred_static
    if use_seg:
        ov = _seg_overlap(sq_ref, sk_ref)
        pred = ov if pred is None else jnp.logical_and(pred, ov)
    if pred is None:
        run()
    else:
        pl.when(pred)(run)


def _fwd_kernel(*refs, scale, causal, causal_offset, block_q,
                block_k, num_kv_blocks, use_seg):
    if use_seg:
        (q_ref, k_ref, v_ref, sq_ref, sk_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    kb = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = kb * block_k

    def run():
        q = q_ref[0, 0].astype(jnp.float32)          # [Bq, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [Bk, D]
        v = v_ref[0, 0].astype(jnp.float32)          # [Bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            # bottom-right alignment (flash-attention-2 / _sdpa_ref tril(k=Sk-Sq)
            # convention): query i attends keys j with j <= i + (Sk - Sq)
            rows = q_start + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if use_seg:
            # varlen/packed sequences: attend only within a segment
            seg_mask = sq_ref[0, :, 0][:, None] == sk_ref[0, :, 0][None, :]
            s = jnp.where(seg_mask, s, _NEG_INF)
        m_prev = m_ref[:, 0]                          # [Bq]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        if use_seg:
            # a row with NO visible keys so far has m_cur == _NEG_INF and
            # s - m_cur == 0 -> exp would emit spurious 1s; zero them
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_cur

    # causal: skip blocks strictly above the (bottom-right-aligned)
    # diagonal; varlen: additionally skip tiles with no same-segment pair
    _gate(k_start <= q_start + block_q - 1 + causal_offset if causal
          else None,
          sq_ref if use_seg else None, sk_ref if use_seg else None,
          use_seg, run)

    @pl.when(kb == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, :, 0] = m_ref[:, 0] + jnp.log(safe_l)


def _seg_operands(seg_q, seg_k, block_q, block_k, q_grid_dim: int = 2):
    """Segment ids as [B, S, 1] with per-batch (1, block, 1) blocks.
    ``q_grid_dim`` names which grid dim walks q blocks (2 for fwd/dq whose
    grid is (B,H,nq,nk); 3 for dkv whose grid is (B,H,nk,nq)).
    Returns ([], []) on the dense path: no operands, no wasted bandwidth."""
    if seg_q is None:
        return [], []
    # [B, S, 1] with (1, block, 1) blocks — same layout family as the
    # lse/delta operands (minor dim 1 equals the array dim, second-to-minor
    # is the 8-divisible block), per-batch DMA traffic
    sq = jnp.asarray(seg_q, jnp.int32)[..., None]
    sk = jnp.asarray(seg_k, jnp.int32)[..., None]
    if q_grid_dim == 2:
        qmap = lambda b, h, i2, i3: (b, i2, 0)  # noqa: E731
        kmap = lambda b, h, i2, i3: (b, i3, 0)  # noqa: E731
    else:
        qmap = lambda b, h, i2, i3: (b, i3, 0)  # noqa: E731
        kmap = lambda b, h, i2, i3: (b, i2, 0)  # noqa: E731
    specs = [pl.BlockSpec((1, block_q, 1), qmap),
             pl.BlockSpec((1, block_k, 1), kmap)]
    return [sq, sk], specs


def _fwd(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k):
    B, H, Sq, D = q.shape
    _, Hk, Sk, _ = k.shape
    group = H // Hk
    nq = Sq // block_q
    nk = Sk // block_k
    seg_ops, seg_specs = _seg_operands(seg_q, seg_k, block_q, block_k)

    grid = (B, H, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          causal_offset=Sk - Sq, block_q=block_q,
                          block_k=block_k, num_kv_blocks=nk,
                          use_seg=bool(seg_ops)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, kb: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, kb, g=group: (b, h // g, kb, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, kb, g=group: (b, h // g, kb, 0)),
            *seg_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, kb: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, kb: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, D), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, *seg_ops)
    return out, lse


def _vmem(shape, dtype):
    return pltpu.VMEM(shape, dtype)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale, causal, causal_offset,
                   block_q, block_k, num_kv_blocks, use_seg):
    if use_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
         dq_ref, acc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, acc_ref) = refs
    kb = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = kb * block_k

    def run():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if use_seg:
            seg_mask = sq_ref[0, :, 0][:, None] == sk_ref[0, :, 0][None, :]
            s = jnp.where(seg_mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if use_seg:  # fully-masked rows have lse == _NEG_INF: avoid exp(0)=1
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _gate(k_start <= q_start + block_q - 1 + causal_offset if causal
          else None,
          sq_ref if use_seg else None, sk_ref if use_seg else None,
          use_seg, run)

    @pl.when(kb == num_kv_blocks - 1)
    def _fin():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, causal_offset, block_q, block_k,
                    num_q_blocks, use_seg):
    if use_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    qb = pl.program_id(3)
    ki = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qb * block_q
    k_start = ki * block_k

    def run():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if use_seg:
            seg_mask = sq_ref[0, :, 0][:, None] == sk_ref[0, :, 0][None, :]
            s = jnp.where(seg_mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                                  # [Bq,Bk]
        if use_seg:
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _gate(k_start <= q_start + block_q - 1 + causal_offset if causal
          else None,
          sq_ref if use_seg else None, sk_ref if use_seg else None,
          use_seg, run)

    @pl.when(qb == num_q_blocks - 1)
    def _fin():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, seg_q, seg_k, out, lse = res
    do, _ = g
    B, H, Sq, D = q.shape
    _, Hk, Sk, _ = k.shape
    group = H // Hk
    nq = Sq // block_q
    nk = Sk // block_k
    seg_ops, seg_specs = _seg_operands(seg_q, seg_k, block_q, block_k,
                                       q_grid_dim=2)
    use_seg = bool(seg_ops)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [B,H,Sq,1]
    lse = lse[..., None] if lse.ndim == 3 else lse

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          causal_offset=Sk - Sq, block_q=block_q,
                          block_k=block_k, num_kv_blocks=nk, use_seg=use_seg),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, kb: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, kb, g_=group: (b, h // g_, kb, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, kb, g_=group: (b, h // g_, kb, 0)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, kb: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, kb: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, kb: (b, h, qi, 0)),
            *seg_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, qi, kb: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[_vmem((block_q, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *seg_ops)

    # dk/dv accumulate over q blocks, one pass per kv head group member then sum
    seg_ops2, seg_specs2 = _seg_operands(seg_q, seg_k, block_q, block_k,
                                         q_grid_dim=3)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          causal_offset=Sk - Sq, block_q=block_q,
                          block_k=block_k, num_q_blocks=nq, use_seg=use_seg),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qb: (b, h, qb, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qb, g_=group: (b, h // g_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qb, g_=group: (b, h // g_, ki, 0)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qb: (b, h, qb, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, ki, qb: (b, h, qb, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, ki, qb: (b, h, qb, 0)),
            *seg_specs2,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qb: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qb: (b, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
        ],
        scratch_shapes=[_vmem((block_k, D), jnp.float32),
                        _vmem((block_k, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *seg_ops2)

    if group > 1:  # GQA: fold query-head groups back onto kv heads
        dk = dk.reshape(B, Hk, group, Sk, D).sum(axis=2)
        dv = dv.reshape(B, Hk, group, Sk, D).sum(axis=2)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None)


# ---------------------------------------------------------------------------
# public entry (custom_vjp, paddle [B, S, H, D] layout)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_bhsd(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k)
    return out, _


def _flash_fwd_rule(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k)
    # checkpoint-policy names: save_only_these_names("flash_out","flash_lse")
    # keeps the kernel's residuals across remat so backward never re-runs
    # the fwd kernel (the dominant recompute term in the full-remat LLaMA
    # step — see BASELINE.md roofline); memory cost is o (bf16) + lse (f32
    # [B,H,S]) per layer, far below the "dots" policies' [B,S,I] saves
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (out, lse), (q, k, v, seg_q, seg_k, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, g):
    return _bwd(scale, causal, block_q, block_k, res, g)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _default_blocks(Sq: int, Sk: int):
    """TPU-tuned defaults (v5e fwd+bwd sweep at S=2048, D=64 and D=128:
    (1024,1024) is ~25% faster than (1024,512) — 11.5/11.9 ms vs 15.4/16.1 —
    and tiny 128x128 blocks are 1.7x SLOWER than the jnp reference).
    Interpret mode (CPU tests) keeps small blocks for speed."""
    if _interpret():
        return min(128, Sq), min(128, Sk)
    return min(1024, Sq), min(1024, Sk)


def flash_attention_with_lse(q, k, v, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             segment_ids=None, kv_segment_ids=None):
    """[B, S, H, D] flash attention returning (out, lse[B, H, S]).

    ``segment_ids`` [B, Sq] (int) enables varlen/packed-sequence masking:
    tokens attend only within their segment (the TPU-native form of the
    reference's ``flash_attn_varlen`` / cu_seqlens API — pack the sequences
    and label each with its index). ``kv_segment_ids`` defaults to
    ``segment_ids`` (self-attention).
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    dq, dk = _default_blocks(Sq, Sk)
    block_q = min(block_q or dq, Sq)
    block_k = min(block_k or dk, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError(f"flash_attention: seq lens ({Sq},{Sk}) must divide "
                         f"block sizes ({block_q},{block_k})")
    if causal and Sq > Sk:
        # bottom-right alignment leaves rows i < Sq-Sk attending nothing; the
        # softmax there is undefined (the jnp oracle yields NaN) — reject rather
        # than return silently wrong finite values
        raise ValueError(f"flash_attention: causal with Sq ({Sq}) > Sk ({Sk}) "
                         f"has fully-masked query rows; mask them explicitly "
                         f"or pad keys")
    if segment_ids is not None and kv_segment_ids is None:
        if Sq != Sk:
            raise ValueError("flash_attention: kv_segment_ids required when "
                             "Sq != Sk")
        kv_segment_ids = segment_ids
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, lse = _flash_bhsd(qt, kt, vt, segment_ids, kv_segment_ids,
                           float(scale), bool(causal),
                           int(block_q), int(block_k))
    return jnp.swapaxes(out, 1, 2), lse[..., 0]


def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    block_q: Optional[int] = None, block_k: Optional[int] = None,
                    segment_ids=None, kv_segment_ids=None):
    """[B, S, H, D] flash attention (the paddle flash_attn kernel equivalent;
    ``segment_ids`` = varlen/packed mode)."""
    out, _ = flash_attention_with_lse(q, k, v, causal, scale, block_q, block_k,
                                      segment_ids, kv_segment_ids)
    return out
