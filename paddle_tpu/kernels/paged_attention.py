"""Pallas flash-decoding paged-attention kernel (the serving decode hot op).

Parity target: the reference's fused paged/block-attention inference kernels
(Paddle Inference's ``block_multihead_attention`` / Phi fusion ops — the
layer PAPER.md credits for production decode speed) and the vLLM/
flash-decoding idiom they implement. The serving engine's XLA fallback path
(``models.generation.paged_decode_step`` gather + ``llama._masked_sdpa``)
materializes a dense ``[slots, W * block_size, Hk, D]`` gather of every
sequence's blocks and then masks most of it away — at long contexts decode
is bandwidth-bound on KV bytes the mask immediately discards.

TPU redesign, not a translation:

* **Block tables consumed IN-KERNEL.** The ``[M, W]`` block table and
  ``[M]`` sequence lengths ride in as scalar-prefetch operands
  (``pltpu.PrefetchScalarGridSpec``), so each grid step's K/V BlockSpec
  index map reads ``table[m, w]`` and DMAs exactly that physical block from
  the pool — the ``[slots, W*bs, ...]`` gather is never materialized in HBM.
* **Split-K across KV blocks, online-softmax merge.** The grid is
  ``(M, Hk, W)`` with the KV-block dimension innermost: each (slot, kv-head)
  cell streams its blocks through VMEM accumulators (running max ``m``,
  normalizer ``l``, weighted-value ``acc``) and merges partials with the
  flash-decoding rescale ``alpha = exp(m_prev - m_cur)`` — the sequential
  spelling of split-K whose parallelism lives in the ``M x Hk`` grid cells
  (the same accumulator scheme as ``flash_attention.py``'s fwd kernel).
* **GQA grouped IN-KERNEL.** Queries arrive as ``[M, Hk, G, D]`` (the
  ``G = H // Hk`` query heads sharing one kv head form one tile), so each
  K/V block is read ONCE per kv head and scored against all its query heads
  — the gather path pays the ``jnp.repeat`` expansion instead.
* **int8 KV dequant fused into the loads.** Quantized pools
  (``kv_quant="int8"``: int8 blocks + per-token-per-head fp32 scales stored
  alongside, see ``models.generation.init_paged_pool``) dequantize in VMEM
  right after the block DMA — HBM only ever streams the int8 bytes, which
  is the capacity AND bandwidth win at once. A dense dequantized pool never
  exists anywhere.
* **Poison containment.** V rows at positions no query may attend
  (``j > seq_len``: the null block, stale tails of reused blocks) are
  zeroed before the PV matmul — the same containment contract as
  ``llama._masked_sdpa`` (0-weight * NaN would otherwise wipe the row), and
  bit-invisible for finite KV since those weights are exact 0.0.

Interpret mode (CPU testing) is selected automatically off the backend via
:mod:`paddle_tpu.kernels.dispatch`, so tier-1 exercises this exact kernel.
Scale layout note: scales are stored ``[N, bs, Hk]`` to match the scatter
writes; on a real TPU the trailing ``Hk`` lane dim is narrow — revisit the
layout if the scale DMA ever shows up in profiles (the K/V streams dominate
by ``D/4``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
try:  # pltpu imports fail on non-TPU builds only at kernel-feature use time
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .dispatch import interpret as _interpret

__all__ = ["paged_attention"]

_NEG_INF = -1e30


def _kernel(*refs, bs, num_blocks_per_seq, scale, quant, G, Q):
    """One grid cell = (slot m, kv head h, KV block w). ``Q = 1`` is the
    single-token decode step; ``Q > 1`` is the speculative-verify entry
    point — the query tile is ``[Q * G, D]`` (Q draft positions x G
    grouped query heads per kv head) and a third scalar-prefetch operand
    ``dl_ref`` carries each slot's draft length: query offset ``i``
    attends ``j <= sl + min(i, dl)`` (its committed KV plus the in-pass
    draft prefix; garbage rows past ``dl`` cap at ``dl`` so no row's
    window ever reaches an unwritten position)."""
    if Q > 1:
        tbl_ref, sl_ref, dl_ref = refs[:3]
        refs = refs[3:]
    else:
        tbl_ref, sl_ref = refs[:2]
        refs = refs[2:]
    if quant:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = \
            refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    m = pl.program_id(0)
    w = pl.program_id(2)

    @pl.when(w == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    sl = sl_ref[m]
    dl = dl_ref[m] if Q > 1 else 0
    base = w * bs

    # skip blocks entirely past the attendable window (their table entries
    # point at the null block; compute is gated, accumulators pass through)
    @pl.when(base <= sl + dl)
    def _run():
        q = q_ref[0, 0].astype(jnp.float32)              # [Q*G, D]
        k = k_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
        v = v_ref[0, :, 0].astype(jnp.float32)
        if quant:                      # dequant fused into the block load
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        j = base + jax.lax.broadcasted_iota(jnp.int32, (bs, 1), 0)[:, 0]
        # containment: V at never-attendable positions must be ZEROED, not
        # merely zero-weighted — a poisoned request can park NaN there
        # (see llama._masked_sdpa); exact 0.0 weights make this bit-invisible
        # for finite KV. The widest window any query row reaches is
        # j <= sl + dl (every position there was written this dispatch or
        # earlier), so the union can never touch a stale block tail.
        v = jnp.where((j <= sl + dl)[:, None], v, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if Q > 1:                      # per-query-row causal draft window
            qi = jax.lax.broadcasted_iota(jnp.int32, (Q * G, 1), 0)[:, 0] // G
            hi = sl + jnp.minimum(qi, dl)                # [Q*G]
            valid = j[None, :] <= hi[:, None]            # [Q*G, bs]
        else:
            valid = (j <= sl)[None, :]                   # [G, bs]
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_cur

    @pl.when(w == num_blocks_per_seq - 1)
    def _finalize():
        l = l_ref[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, block_tables, seq_lens,
                    draft_lens=None, k_scale=None, v_scale=None,
                    scale: Optional[float] = None, out_dtype=None):
    """Decode attention for ``M`` serving slots straight off the block pool.

    ``q [M, H, D]`` — one query token per slot (the decode entry point) —
    or ``q [M, Q, H, D]`` with ``draft_lens [M]`` — ``Q`` query tokens
    per slot, the SPECULATIVE-VERIFY entry point: query offset ``i`` of
    slot ``m`` sits at KV position ``seq_lens[m] + i`` and attends ``j <=
    seq_lens[m] + min(i, draft_lens[m])`` (committed KV plus the in-pass
    draft prefix; rows past the slot's real draft cap at ``draft_lens``
    so no window reaches an unwritten position). ``k_pool``/``v_pool``
    ``[N, bs, Hk, D]`` — ONE layer's physical block pool (fp, or int8 with
    ``k_scale``/``v_scale [N, bs, Hk]`` fp32 per-token-per-head scales);
    ``block_tables [M, W]`` int32 — slot ``m``'s KV position ``j`` lives in
    physical block ``block_tables[m, j // bs]`` at offset ``j % bs``;
    ``seq_lens [M]`` int32 — slot ``m`` attends positions ``j <=
    seq_lens[m]`` (its new token's KV was just scattered at ``seq_lens[m]``).
    Unassigned table entries must point at the null block 0. Returns
    ``[M, H, D]`` (or ``[M, Q, H, D]``) in ``out_dtype`` (default: the
    pool dtype for fp pools, fp32 for int8 pools — matching the gather
    path's ``_masked_sdpa`` output dtype).
    """
    multi = q.ndim == 4
    if multi:
        M, Q, H, D = q.shape
        if draft_lens is None:
            raise ValueError("paged_attention: multi-query (verify) calls "
                             "need draft_lens")
    else:
        M, H, D = q.shape
        Q = 1
        if draft_lens is not None:
            raise ValueError("paged_attention: draft_lens given with a "
                             "single-token q [M, H, D]; the verify entry "
                             "point takes q [M, Q, H, D]")
    N, bs, Hk, _ = k_pool.shape
    W = block_tables.shape[1]
    if H % Hk:
        raise ValueError(f"paged_attention: {H} query heads not divisible "
                         f"by {Hk} kv heads")
    G = H // Hk
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("paged_attention: k_scale and v_scale must be "
                         "given together")
    if out_dtype is None:
        out_dtype = jnp.float32 if quant else k_pool.dtype
    scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    # GQA grouping: query head h = kh * G + g shares kv head kh — exactly
    # the jnp.repeat(k, G, axis=heads) correspondence the fallback expands.
    # Multi-query tiles stack the Q draft positions above the group: row
    # q * G + g of kv head kh is query offset q's head kh * G + g.
    if multi:
        qg = q.reshape(M, Q, Hk, G, D).transpose(0, 2, 1, 3, 4) \
              .reshape(M, Hk, Q * G, D)
    else:
        qg = q.reshape(M, Hk, G, D)
    QG = Q * G
    tbl = jnp.asarray(block_tables, jnp.int32)
    sl = jnp.asarray(seq_lens, jnp.int32)
    # scalar-prefetch operands: (tbl, sl) for decode, + dl for verify —
    # every index map takes them positionally after the grid indices
    if multi:
        scalars = (tbl, sl, jnp.asarray(draft_lens, jnp.int32))

        def qmap(m, h, w, tbl, sl, dl):
            return (m, h, 0, 0)

        def kvmap(m, h, w, tbl, sl, dl):
            return (tbl[m, w], 0, h, 0)

        def smap(m, h, w, tbl, sl, dl):
            return (tbl[m, w], 0, h)
    else:
        scalars = (tbl, sl)

        def qmap(m, h, w, tbl, sl):
            return (m, h, 0, 0)

        def kvmap(m, h, w, tbl, sl):
            return (tbl[m, w], 0, h, 0)

        def smap(m, h, w, tbl, sl):
            return (tbl[m, w], 0, h)

    in_specs = [
        pl.BlockSpec((1, 1, QG, D), qmap),
        pl.BlockSpec((1, bs, 1, D), kvmap),
        pl.BlockSpec((1, bs, 1, D), kvmap),
    ]
    ops = [qg, k_pool, v_pool]
    if quant:
        in_specs += [pl.BlockSpec((1, bs, 1), smap),
                     pl.BlockSpec((1, bs, 1), smap)]
        ops += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(M, Hk, W),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, QG, D), qmap),
        scratch_shapes=[
            pltpu.VMEM((QG, D), jnp.float32),
            pltpu.VMEM((QG, 1), jnp.float32),
            pltpu.VMEM((QG, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, num_blocks_per_seq=W, scale=scale,
                          quant=quant, G=G, Q=Q),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((M, Hk, QG, D), out_dtype),
        interpret=_interpret(),
    )(*scalars, *ops)
    if multi:
        return out.reshape(M, Hk, Q, G, D).transpose(0, 2, 1, 3, 4) \
                  .reshape(M, Q, H, D)
    return out.reshape(M, H, D)
