"""Weight-only int8 matmul Pallas kernel.

Parity target: the reference's weight-only quantization path
(``paddle.nn.quant.weight_only_linear`` / ``llm.int8`` kernels under
``paddle/phi/kernels/fusion/``). TPU rationale: LLM inference matmuls are
HBM-BANDWIDTH bound on the weight stream — storing W as int8 + a per-column
fp scale halves the bytes read per step vs bf16. The kernel streams int8
blocks into VMEM, dequantizes in-register, and feeds the MXU in bf16; the
XLA-composed equivalent (``x @ (w.astype(bf16) * scale)``) materializes the
dequantized [K, N] matrix through HBM when it can't fuse, paying the full
bf16 bandwidth.

API:
  * :func:`quantize_weights`  — symmetric per-column int8 quantization.
  * :func:`weight_only_matmul` — ``x [..., K] @ w_int8 [K, N] -> [..., N]``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .dispatch import interpret as _interpret

__all__ = ["quantize_weights", "weight_only_matmul"]


def quantize_weights(w) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of ``w [K, N]``:
    returns ``(w_int8 [K, N], scale [N])`` with ``w ≈ w_int8 * scale``."""
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    wb = w_ref[...].astype(jnp.bfloat16)          # int8 -> bf16 in VMEM
    acc_ref[...] += jnp.dot(x_ref[...], wb,
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _out():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def weight_only_matmul(x, w_q, scale, *, block_m: Optional[int] = None,
                       block_n: int = 512, block_k: int = 512,
                       out_dtype=jnp.bfloat16):
    """``x [..., K] (bf16) @ dequant(w_q [K, N] int8, scale [N]) ->
    [..., N]``; the dequantization happens in VMEM, so HBM only ever sees
    the int8 weights (the whole point)."""
    x = jnp.asarray(x)
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = w_q.shape[1]
    xm = x.reshape(-1, K)
    M = xm.shape[0]
    bm = block_m or min(256, max(8, M))
    bn = min(block_n, N)
    bk = min(block_k, K)

    def xla_fallback():
        out = xm.astype(jnp.bfloat16) @ (
            w_q.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)[None, :])
        return out.astype(out_dtype).reshape(*lead, N)

    if pltpu is None:
        return xla_fallback()        # no VMEM scratch without pallas.tpu
    if M % bm or N % bn or K % bk:
        return xla_fallback()        # shape not blockable
    nk = K // bk
    grid = (M // bm, N // bn, nk)

    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
            # scale as [1, N]: 1-D operands clash with XLA's tiled layout
            pl.BlockSpec((1, bn), lambda m, n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_interpret(),
    )(xm.astype(jnp.bfloat16), w_q, scale.reshape(1, N))
    return out.reshape(*lead, N)
