"""Pallas fused RMSNorm (forward + backward).

Parity target: the reference's ``fused_rms_norm`` GPU kernel
(``paddle/phi/kernels/fusion/gpu/`` fused_rms_norm / rms_norm_kernel). TPU redesign:
one VMEM-resident Pallas kernel computing x * rsqrt(mean(x^2)+eps) * w row-blockwise
(saves the rstd for backward); backward is a second kernel producing dx and a
per-row-block partial dw reduced on the host side of the kernel boundary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .dispatch import interpret as _interpret

__all__ = ["rms_norm"]


def _fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dwp_ref):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x * rstd
    wg = g * w
    # dx = rstd * (wg - xhat * mean(wg * xhat, -1))
    m = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (wg - xhat * m)).astype(dx_ref.dtype)

    @pl.when(i == 0)
    def _():
        dwp_ref[:] = jnp.zeros_like(dwp_ref)

    # accumulate the weight grad across row blocks (same (8, d) block revisited
    # every grid step; every sublane row carries the full sum — row 0 is read back)
    part = jnp.sum(g * xhat, axis=0, keepdims=True)
    dwp_ref[:] += jnp.broadcast_to(part, dwp_ref.shape)


def _block_rows(n_rows: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n_rows % b == 0:
            return b
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm over the last axis: x * rsqrt(mean(x^2)+eps) * weight."""
    out, _ = _fwd(x, weight, eps)
    return out


def _fwd(x, weight, eps):
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    br = _block_rows(n)
    out, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, weight.reshape(1, d))
    return out.reshape(shape), rstd


def _rms_fwd_rule(x, weight, eps):
    out, rstd = _fwd(x, weight, eps)
    return out, (x, weight, rstd)


def _rms_bwd_rule(eps, res, g):
    x, weight, rstd = res
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    g2 = g.reshape(-1, d)
    n = x2.shape[0]
    br = _block_rows(n)
    dx, dwp = pl.pallas_call(
        _bwd_kernel,
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((8, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((8, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, weight.reshape(1, d), rstd, g2)
    dw = dwp[0].astype(weight.dtype)
    return dx.reshape(shape), dw


rms_norm.defvjp(_rms_fwd_rule, _rms_bwd_rule)
