"""Fused rotary position embedding.

Parity target: the reference's ``fused_rope`` kernel
(``paddle/phi/kernels/fusion/gpu/fused_rope_*``). TPU redesign: the rotate-half
formulation as a single VMEM-resident Pallas kernel over [rows, head_dim] blocks;
backward is the same rotation with the angle sign flipped (exact adjoint), via
custom_vjp so no trig recomputation graph is kept.

Layout: q/k as [B, S, H, D]; cos/sin as [S, D] (broadcast over batch and heads).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .dispatch import interpret as _interpret

__all__ = ["apply_rope", "rope_cos_sin"]


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[0].astype(jnp.float32)            # [S, D]
    cos = cos_ref[:].astype(jnp.float32)
    sin = sin_ref[:].astype(jnp.float32)
    d = x.shape[-1]
    x1 = x[:, : d // 2]
    x2 = x[:, d // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    o_ref[0] = (x * cos + rot * sin).astype(o_ref.dtype)


def _run(x, cos, sin):
    B, S, H, D = x.shape
    xf = jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)
    out = pl.pallas_call(
        _rope_kernel,
        grid=(B * H,),
        in_specs=[
            pl.BlockSpec((1, S, D), lambda i: (i, 0, 0)),
            pl.BlockSpec((S, D), lambda i: (0, 0)),
            pl.BlockSpec((S, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, S, D), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), x.dtype),
        interpret=_interpret(),
    )(xf, cos, sin)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)


@jax.custom_vjp
def apply_rope(x, cos, sin):
    """Rotate-half RoPE: x*cos + rotate_half(x)*sin on [B, S, H, D]."""
    return _run(x, cos, sin)


def _rope_fwd(x, cos, sin):
    return _run(x, cos, sin), (cos, sin)


def _rope_bwd(res, g):
    cos, sin = res
    # adjoint of the rotation = rotation by -theta
    return _run(g, cos, -sin), None, None


apply_rope.defvjp(_rope_fwd, _rope_bwd)


def rope_cos_sin(seq_len: int, head_dim: int, base: float = 10000.0,
                 dtype=jnp.float32, position_ids=None):
    """cos/sin tables [S, D] for the rotate-half convention."""
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    pos = (jnp.arange(seq_len, dtype=jnp.float32) if position_ids is None
           else jnp.asarray(position_ids, jnp.float32))
    freqs = jnp.outer(pos, inv)                  # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)
