"""paddle.linalg namespace (parity: python/paddle/tensor/linalg.py public exports +
python/paddle/linalg.py in the reference)."""

from .ops.linalg import (lu, lu_unpack, matrix_exp, ormqr,
                         svd_lowrank, bmm, cholesky, cholesky_solve, cond, corrcoef, cov, det,
                         dist, eig, eigh, eigvals, eigvalsh, einsum,
                         householder_product, inv, lstsq, matmul, matrix_norm,
                         matrix_power, matrix_rank, multi_dot, mv, norm, pinv, qr,
                         slogdet, solve, svd, svdvals, t, triangular_solve,
                         vector_norm)
from .ops.math import cross, dot

__all__ = [
    "bmm", "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "dist",
    "eig", "eigh", "eigvals", "eigvalsh", "einsum", "householder_product", "inv",
    "lstsq", "matmul", "matrix_norm", "matrix_power", "matrix_rank", "multi_dot",
    "mv", "norm", "pinv", "qr", "slogdet", "solve", "svd", "svdvals", "t",
    "triangular_solve", "vector_norm", "cross", "dot", "lu",
    "lu_unpack", "matrix_exp", "ormqr", "svd_lowrank",
]
