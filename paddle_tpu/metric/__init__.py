"""``paddle.metric`` parity: streaming metrics.

Reference surface: ``python/paddle/metric/metrics.py`` (Metric base,
Accuracy, Precision, Recall, Auc) — accumulate over batches on host numpy
(metrics are not in the compiled hot path), ``reset``/``update``/
``accumulate``/``name`` protocol used by hapi ``Model.fit``.
"""

from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        """Optional pre-processing run inside the program; default identity."""
        return pred, label


class Accuracy(Metric):
    """top-k accuracy (ref: metric.Accuracy; default k=1)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)  # noqa: E741
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]  # noqa: E741
        maxk = max(self.topk)
        top = np.argsort(-p, axis=-1)[..., :maxk]
        return (top == l[..., None]).astype(np.float32)

    def update(self, correct, *args):
        c = _np(correct)
        n = int(np.prod(c.shape[:-1]))
        for i, k in enumerate(self.topk):
            self.total[i] += c[..., :k].sum()
            self.count[i] += n
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """binary precision over 0/1 labels (ref: metric.Precision)."""

    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return float(self.tp) / d if d else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return float(self.tp) / d if d else 0.0


class Auc(Metric):
    """ROC AUC via the reference's threshold-bucket approximation
    (ref: metric.Auc, num_thresholds buckets)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        if curve != "ROC":
            raise ValueError("only ROC curve is supported (reference parity)")
        self.num_thresholds = int(num_thresholds)
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]  # probability of the positive class
        p = p.reshape(-1)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[l == 1], 1)
        np.add.at(self._neg, idx[l == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # walk buckets from the highest threshold down
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional top-k accuracy (ref: paddle.metric.accuracy)."""
    from ..core.tensor import to_tensor
    p = _np(input)
    l = _np(label)  # noqa: E741
    if l.ndim == p.ndim and l.shape[-1] == 1:
        l = l[..., 0]  # noqa: E741
    top = np.argsort(-p, axis=-1)[..., :k]
    acc = (top == l[..., None]).any(-1).mean()
    return to_tensor(np.asarray(acc, np.float32))
