"""``paddle.metric`` parity: streaming metrics.

Reference surface: ``python/paddle/metric/metrics.py`` (Metric base,
Accuracy, Precision, Recall, Auc) — accumulate over batches on host numpy
(metrics are not in the compiled hot path), ``reset``/``update``/
``accumulate``/``name`` protocol used by hapi ``Model.fit``.
"""

from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        """Optional pre-processing run inside the program; default identity."""
        return pred, label


class Accuracy(Metric):
    """top-k accuracy (ref: metric.Accuracy; default k=1)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)  # noqa: E741
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]  # noqa: E741
        maxk = max(self.topk)
        top = np.argsort(-p, axis=-1)[..., :maxk]
        return (top == l[..., None]).astype(np.float32)

    def update(self, correct, *args):
        c = _np(correct)
        n = int(np.prod(c.shape[:-1]))
        for i, k in enumerate(self.topk):
            self.total[i] += c[..., :k].sum()
            self.count[i] += n
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """binary precision over 0/1 labels (ref: metric.Precision)."""

    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return float(self.tp) / d if d else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return float(self.tp) / d if d else 0.0


class Auc(Metric):
    """ROC AUC via the reference's threshold-bucket approximation
    (ref: metric.Auc, num_thresholds buckets)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        if curve != "ROC":
            raise ValueError("only ROC curve is supported (reference parity)")
        self.num_thresholds = int(num_thresholds)
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]  # probability of the positive class
        p = p.reshape(-1)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[l == 1], 1)
        np.add.at(self._neg, idx[l == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # walk buckets from the highest threshold down
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional top-k accuracy (ref: paddle.metric.accuracy)."""
    from ..core.tensor import to_tensor
    p = _np(input)
    l = _np(label)  # noqa: E741
    if l.ndim == p.ndim and l.shape[-1] == 1:
        l = l[..., 0]  # noqa: E741
    top = np.argsort(-p, axis=-1)[..., :k]
    acc = (top == l[..., None]).any(-1).mean()
    return to_tensor(np.asarray(acc, np.float32))


# ---------------------------------------------------------------------------
# r5: functional metric ops (ref: accuracy_op is above; auc_op,
# precision_recall_op, positive_negative_pair_op in
# paddle/fluid/operators/metrics/). Pure functional forms — the stateful
# accumulators are the Metric classes above.
# ---------------------------------------------------------------------------

def auc(input, label, num_thresholds: int = 4095, curve: str = "ROC",  # noqa: A002
        name=None):
    """ref: auc_op — trapezoidal ROC AUC over a threshold histogram.
    ``input [N, 2]`` (prob of class 1 in col 1) or [N] probs."""
    import numpy as _np2
    from ..core.tensor import to_tensor
    p = _np(input)
    y = _np(label).reshape(-1)
    if p.ndim == 2:
        p = p[:, 1]
    bins = _np2.clip((p * num_thresholds).astype(int), 0, num_thresholds)
    pos_h = _np2.bincount(bins[y == 1], minlength=num_thresholds + 1)
    neg_h = _np2.bincount(bins[y != 1], minlength=num_thresholds + 1)
    # descending threshold cumulative
    tp = _np2.cumsum(pos_h[::-1])
    fp = _np2.cumsum(neg_h[::-1])
    tot_p = max(int(tp[-1]), 1)
    tot_n = max(int(fp[-1]), 1)
    tpr = tp / tot_p
    fpr = fp / tot_n
    a = float(_np2.trapezoid(tpr, fpr))
    return to_tensor(_np2.float32(a))


def precision_recall(input, label, num_classes=None, name=None):  # noqa: A002
    """ref: precision_recall_op — per-class and macro/micro
    precision/recall/F1. ``input [N, C]`` scores, ``label [N]``. Returns a
    [C + 2, 3] Tensor: per-class rows then (macro, micro) rows of
    (precision, recall, f1)."""
    import numpy as _np2
    from ..core.tensor import to_tensor
    s = _np(input)
    y = _np(label).reshape(-1)
    C = num_classes or s.shape[1]
    pred = s.argmax(-1)
    rows = []
    tps = fps = fns = 0
    for c in range(C):
        tp = int(((pred == c) & (y == c)).sum())
        fp = int(((pred == c) & (y != c)).sum())
        fn = int(((pred != c) & (y == c)).sum())
        tps, fps, fns = tps + tp, fps + fp, fns + fn
        pr = tp / max(tp + fp, 1)
        rc = tp / max(tp + fn, 1)
        f1 = 2 * pr * rc / max(pr + rc, 1e-12)
        rows.append((pr, rc, f1))
    macro = tuple(float(_np2.mean([r[i] for r in rows])) for i in range(3))
    mpr = tps / max(tps + fps, 1)
    mrc = tps / max(tps + fns, 1)
    micro = (mpr, mrc, 2 * mpr * mrc / max(mpr + mrc, 1e-12))
    return to_tensor(_np2.asarray(rows + [macro, micro], _np2.float32))


def positive_negative_pair(score, label, query_id, name=None):
    """ref: positive_negative_pair_op (ranking eval): within each query,
    count pairs ordered correctly (positive), incorrectly (negative), or
    tied (neutral). Returns (positive, negative, neutral) counts."""
    import numpy as _np2
    from ..core.tensor import to_tensor
    s = _np(score).reshape(-1)
    y = _np(label).reshape(-1)
    q = _np(query_id).reshape(-1)
    pos = neg = neu = 0
    for qid in _np2.unique(q):
        m = q == qid
        ss, yy = s[m], y[m]
        for i in range(len(ss)):
            for j in range(i + 1, len(ss)):
                if yy[i] == yy[j]:
                    continue
                hi, lo = (i, j) if yy[i] > yy[j] else (j, i)
                if ss[hi] > ss[lo]:
                    pos += 1
                elif ss[hi] < ss[lo]:
                    neg += 1
                else:
                    neu += 1
    return (to_tensor(_np2.float32(pos)), to_tensor(_np2.float32(neg)),
            to_tensor(_np2.float32(neu)))


__all__ += ["auc", "precision_recall", "positive_negative_pair"]


def _register_metric_ops():
    from ..core.dispatch import OP_REGISTRY, register_op
    for _n in ["accuracy", "auc", "precision_recall",
               "positive_negative_pair"]:
        _f = globals()[_n]
        if _n not in OP_REGISTRY:
            register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                        differentiable=False, category="metric", public=_f)


_register_metric_ops()
