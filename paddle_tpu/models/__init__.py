"""Flagship model zoo (the reference ships these via PaddleNLP/PaddleClas —
SURVEY §2.6 ecosystem row; here they are first-class so the framework is
benchmarkable end-to-end).

``llama`` is the flagship decoder family: a pure-functional, scan-over-stacked-
layers implementation designed for XLA (single trace regardless of depth,
pipeline-ready stacked params) plus sharding-spec builders for the hybrid mesh.
"""

from . import bert, llama  # noqa: F401
from .bert import (BertConfig, BertForPretraining,
                   BertForSequenceClassification, BertModel)  # noqa: F401
from .llama import (LlamaConfig, LlamaForCausalLM, init_params, forward,
                    loss_fn, param_specs)  # noqa: F401
