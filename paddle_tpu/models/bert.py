"""BERT-family encoder — the fine-tune benchmark model.

Capability target: PaddleNLP's BERT/ERNIE implementation
(``paddlenlp/transformers/bert/modeling.py`` — SURVEY §2.6 ecosystem row;
BERT-base fine-tune is a BASELINE.md config). Built on the framework's own
``nn.TransformerEncoder`` stack (pre/post-norm, SDPA -> flash-attention on
TPU), eager Layers + ``to_static``-compilable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..nn import (Dropout, Embedding, LayerNorm, Linear, Tanh,
                  TransformerEncoder, TransformerEncoderLayer)
from ..nn.layer import Layer

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForPretraining", "bert_init_params", "bert_encode"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ..ops.creation import arange, zeros_like
        from ..ops.manipulation import expand
        B, S = input_ids.shape
        if position_ids is None:
            position_ids = expand(arange(S, dtype="int64"), [B, S])
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)
        self.activation = Tanh()

    def forward(self, hidden):
        return self.activation(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """ref: paddlenlp BertModel (embeddings + encoder + pooler)."""

    def __init__(self, config: BertConfig = None, **kwargs):
        super().__init__()
        cfg = config or BertConfig(**kwargs)
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0, layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None:
            # [B, S] 1/0 mask -> additive [B, 1, 1, S]: 0 visible, -1e4 masked
            from ..ops.manipulation import reshape
            from ..ops.math import cast
            m = cast(attention_mask, "float32")
            B, S = input_ids.shape
            attention_mask = (reshape(m, [B, 1, 1, S]) - 1.0) * 1e4
        seq = self.encoder(x, attention_mask)
        return seq, self.pooler(seq)


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig = None, num_classes: int = 2,
                 dropout: Optional[float] = None, **kwargs):
        super().__init__()
        self.bert = BertModel(config, **kwargs)
        cfg = self.bert.config
        self.dropout = Dropout(dropout if dropout is not None
                               else cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)
        self.num_classes = num_classes

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            from ..nn import functional as F
            return F.cross_entropy(logits, labels), logits
        return logits


# ---------------------------------------------------------------------------
# functional JAX encoder — the serving engine's EMBEDDINGS model (ISSUE 19)
# ---------------------------------------------------------------------------
#
# The eager Layer classes above are the fine-tune benchmark surface; the
# serving engine's prefill-only embeddings endpoint needs the same shape in
# the engine's idiom instead: a pure (params, ids, lengths) -> pooled [B, E]
# function over STACKED per-layer params (lax.scan over [L, ...] leaves,
# exactly like the llama paged path), jitted per length bucket by
# ``ServingEngine``. Post-norm BERT blocks, bidirectional length-masked
# attention, first-token tanh pooler — ``BertModel``'s semantics, minus
# dropout (inference) and token-type embeddings (single-segment requests).

def bert_init_params(cfg: BertConfig, seed: int = 0):
    """Random stacked encoder params (fp32 jnp pytree): embeddings
    (word + position + LayerNorm), ``num_hidden_layers`` stacked
    transformer blocks, and the pooler dense."""
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    E, I = cfg.hidden_size, cfg.intermediate_size
    L = cfg.num_hidden_layers

    def w(*shape, scale=0.02):
        return jnp.asarray(rng.standard_normal(shape) * scale, jnp.float32)

    def ones(*shape):
        return jnp.ones(shape, jnp.float32)

    def zeros(*shape):
        return jnp.zeros(shape, jnp.float32)

    return {
        "embed": w(cfg.vocab_size, E),
        "pos_embed": w(cfg.max_position_embeddings, E),
        "ln_embed_w": ones(E), "ln_embed_b": zeros(E),
        "layers": {
            "wq": w(L, E, E), "bq": zeros(L, E),
            "wk": w(L, E, E), "bk": zeros(L, E),
            "wv": w(L, E, E), "bv": zeros(L, E),
            "wo": w(L, E, E), "bo": zeros(L, E),
            "ln_attn_w": ones(L, E), "ln_attn_b": zeros(L, E),
            "w_in": w(L, E, I), "b_in": zeros(L, I),
            "w_out": w(L, I, E), "b_out": zeros(L, E),
            "ln_mlp_w": ones(L, E), "ln_mlp_b": zeros(L, E),
        },
        "pool_w": w(E, E), "pool_b": zeros(E),
    }


def _bert_ln(x, w, b, eps):
    import jax.numpy as jnp
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def bert_encode(params, cfg: BertConfig, ids, lengths):
    """Pooled sentence embeddings for a right-padded batch: ``ids [B, S]``
    int32, ``lengths [B]`` real token counts -> ``[B, E]`` fp32 (the
    first-token tanh pooler, ``BertPooler``'s contract). Pure and
    jit-friendly — the serving engine compiles one program per
    ``(B, S)`` bucket and batches queued embedding requests into it; pad
    rows (``lengths == 0``) attend only themselves and their pooled rows
    are garbage the engine never reads."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    B, S = ids.shape
    H = cfg.num_attention_heads
    E = cfg.hidden_size
    D = E // H
    eps = cfg.layer_norm_eps
    x = (jnp.take(params["embed"], ids, axis=0)
         + params["pos_embed"][None, :S])
    x = _bert_ln(x, params["ln_embed_w"], params["ln_embed_b"], eps)
    j = jnp.arange(S)
    # bidirectional length mask (keys beyond a row's length are invisible);
    # pad rows get their own position 0 so softmax stays finite
    visible = j[None, :] < jnp.maximum(lengths, 1)[:, None]     # [B, S]
    bias = jnp.where(visible, 0.0, -1e9)[:, None, None, :]      # [B,1,1,S]

    def body(h, lp):
        q = (h @ lp["wq"] + lp["bq"]).reshape(B, S, H, D)
        k = (h @ lp["wk"] + lp["bk"]).reshape(B, S, H, D)
        v = (h @ lp["wv"] + lp["bv"]).reshape(B, S, H, D)
        scores = jnp.einsum("bshd,bthd->bhst", q, k) / jnp.sqrt(
            jnp.float32(D))
        p = jax.nn.softmax(scores + bias, axis=-1)
        o = jnp.einsum("bhst,bthd->bshd", p, v).reshape(B, S, E)
        h = _bert_ln(h + (o @ lp["wo"] + lp["bo"]),
                     lp["ln_attn_w"], lp["ln_attn_b"], eps)
        f = jax.nn.gelu(h @ lp["w_in"] + lp["b_in"]) @ lp["w_out"] \
            + lp["b_out"]
        h = _bert_ln(h + f, lp["ln_mlp_w"], lp["ln_mlp_b"], eps)
        return h, None

    x, _ = lax.scan(body, x, params["layers"])
    pooled = jnp.tanh(x[:, 0] @ params["pool_w"] + params["pool_b"])
    return pooled.astype(jnp.float32)


class BertForPretraining(Layer):
    """MLM + NSP heads (ref: BertForPretraining)."""

    def __init__(self, config: BertConfig = None, **kwargs):
        super().__init__()
        self.bert = BertModel(config, **kwargs)
        cfg = self.bert.config
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_ln = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps)
        self.decoder = Linear(cfg.hidden_size, cfg.vocab_size)
        self.seq_relationship = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        from ..nn import functional as F
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        h = self.transform_ln(F.gelu(self.transform(seq)))
        mlm_logits = self.decoder(h)
        nsp_logits = self.seq_relationship(pooled)
        if masked_lm_labels is None:
            return mlm_logits, nsp_logits
        mlm = F.cross_entropy(
            mlm_logits.reshape([-1, mlm_logits.shape[-1]]),
            masked_lm_labels.reshape([-1]), ignore_index=-100)
        loss = mlm
        if next_sentence_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits,
                                          next_sentence_labels)
        return loss
