"""Autoregressive generation with a KV cache — TPU decode done the XLA way.

Capability target: the reference ecosystem's ``generate()`` surface
(PaddleNLP ``generation_utils.py`` — greedy / sampling with top-k/top-p,
eos handling, ragged prompt batches; SURVEY §2.6 ecosystem row).

TPU redesign, not a translation:

* **One compiled program.** Prefill + the whole decode loop run inside a
  single ``jax.jit`` — the decode loop is a ``lax.while_loop`` over token
  steps with an ALIVE-MASK EARLY EXIT (a batch whose rows all hit eos at
  step k pays k steps, not max_new_tokens; greedy outputs stay
  bit-identical because skipped steps would only have emitted pad), so
  there is no per-token Python dispatch (the reference's per-token Python
  loop is exactly the pattern SURVEY §3.1 warns against on TPU).
* **Static cache layout.** The KV cache is a stacked ``[L, B, C, Hk, D]``
  pytree with a *static* capacity ``C = prompt_len + max_new_tokens``; every
  decode step writes at a uniform scalar index via
  ``lax.dynamic_update_slice`` — no dynamic shapes anywhere, so XLA keeps the
  whole loop on-device and updates the cache in place (buffer reuse inside
  the program; the streaming API additionally donates the cache across
  dispatches).
* **Left-aligned ragged batches.** Ragged prompts are left-padded
  internally: every row's last prompt token then sits at the same index, the
  prefill's final-position logits are a plain ``h[:, -1]`` slice, and decode
  writes land at one scalar index for all rows (a right-padded layout would
  need per-row scatter indices).
* **Streaming tier.** :class:`DecodeSession` exposes prefill/step as two
  jitted functions with the cache DONATED between dispatches, for callers
  that need a token at a time (``inference.Predictor`` wiring, speculative
  clients). Same kernels, same cache layout.
* **Paged tier.** :func:`init_paged_pool` / :func:`paged_prefill` /
  :func:`paged_decode_step` are the block-table attention entry points the
  continuous-batching serving engine drives (``inference.serving``,
  docs/SERVING.md): one physical block pool shared by every slot,
  gather-based attention over each sequence's own blocks, token-level
  bit-parity with the dense cache path pinned by tests/test_serving.py.

MoE caveat: GShard routing capacity is evaluated per forward call, so a
decode step routes B tokens in isolation while a full no-cache forward
routes B*S jointly — when capacity DROPS occur the two paths can diverge
(both are "correct" MoE inference; drops are a training-throughput knob).
Exact greedy parity with the full-forward oracle therefore holds when no
tokens are dropped, which is the regime inference runs in (per-step load
of B tokens over E experts rarely exceeds capacity).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .llama import (LlamaConfig, _masked_sdpa, _mm, _moe_ffn, _rms_norm,
                    _rope)
from .lora import lora_delta

__all__ = ["GenerationConfig", "init_cache", "prefill", "decode_step",
           "make_generate_fn", "generate", "DecodeSession",
           "init_paged_pool", "paged_pool_block_bytes", "paged_pool_specs",
           "paged_prefill", "paged_prefill_chunk", "paged_decode_step",
           "paged_spec_step", "paged_mixed_step", "sample_tokens",
           "seed_key",
           "validate_sampling", "validate_tp"]


# ---------------------------------------------------------------------------
# sampling-knob config (the ONE struct shared by every decode tier)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GenerationConfig:
    """Sampling knobs (ref: PaddleNLP GenerationConfig).

    The single source of truth for every decode tier: the functional
    :func:`generate`, the eager ``LlamaForCausalLM.generate`` kwargs
    surface, ``inference.GenerationPredictor``, and the serving engine
    (``inference.serving``) all resolve through this one struct — the two
    previously-duplicated knob sets (``inference.generation``'s class vs
    the eager wrapper's kwargs) are gone.
    """

    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    # the PRNG seed every sampling tier resolves (the previously-hardcoded
    # jax.random.PRNGKey(0) default of the dense generate() path, folded
    # into the ONE config): dense generate derives its key from it when
    # the caller passes none, and the serving engine derives each
    # request's per-slot base key from it — outputs are reproducible per
    # (request, seed) across preemption, crash resubmit and failover
    seed: int = 0

    def replace(self, **kw) -> "GenerationConfig":
        return dataclasses.replace(self, **kw)

    # knobs for which None is a VALUE (disable), not the unset spelling
    _NONEABLE = frozenset({"top_k", "top_p", "eos_token_id"})

    @classmethod
    def resolve(cls, generation_config: Optional["GenerationConfig"] = None,
                **overrides) -> "GenerationConfig":
        """Merge a kwargs surface onto an optional base config. The string
        ``"unset"`` always means "not given" (keeps the base's field; the
        same sentinel ``ServingEngine.submit`` uses). For the Optional
        knobs (``top_k``/``top_p``/``eos_token_id``) ``None`` is a real
        override — ``eos_token_id=None`` disables EOS even when the base
        config sets one; for every other field ``None`` means "not given"
        (None is never a valid value for them, e.g. ``pad_token_id=None``
        keeps the base's pad id)."""
        base = generation_config if generation_config is not None else cls()
        updates = {k: v for k, v in overrides.items()
                   if not (isinstance(v, str) and v == "unset")
                   and not (v is None and k not in cls._NONEABLE)}
        return dataclasses.replace(base, **updates) if updates else base


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

def init_cache(cfg: LlamaConfig, batch: int, capacity: int,
               dtype=None) -> Dict:
    """Stacked KV cache ``{"k","v": [L, B, C, Hk, D]}`` (static capacity)."""
    dt = dtype if dtype is not None else cfg.dtype
    shape = (cfg.num_hidden_layers, batch, capacity, cfg.kv_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _cached_layer(lp: Dict, x, ck, cv, cos, sin, kv_mask, write_idx,
                  cfg: LlamaConfig):
    """One decoder block attending against the cache.

    ``x [B, T, E]`` (T = prompt length for prefill, 1 for decode);
    ``ck/cv [B, C, Hk, D]`` this layer's cache; ``kv_mask [B, T, C]`` True
    where query t may attend key position j; ``write_idx`` scalar — the new
    K/V rows are written at cache positions [write_idx, write_idx+T).
    Returns ``(y, ck, cv)``. MoE configs also apply the routed FFN (aux loss
    is irrelevant at inference and dropped).
    """
    B, T, E = x.shape
    H, Hk, D = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = _rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
    q = _mm(h, lp, "wq", dt).reshape(B, T, H, D)
    k = _mm(h, lp, "wk", dt).reshape(B, T, Hk, D)
    v = _mm(h, lp, "wv", dt).reshape(B, T, Hk, D)
    q = _rope(q, cos, sin, False)
    k = _rope(k, cos, sin, False)

    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, write_idx, 0, 0))
    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, write_idx, 0, 0))

    o = _masked_sdpa(q, ck, cv, kv_mask)
    x = x + _mm(o.reshape(B, T, H * D).astype(dt), lp, "wo", dt)

    x, drops = _ffn_tail(lp, x, cfg)
    return x, ck, cv, drops


def _ffn_tail(lp: Dict, x, cfg: LlamaConfig):
    """The post-attention half of a decoder block on ``x [B, T, E]``:
    pre-norm + dense SwiGLU or the routed MoE FFN. Returns
    ``(block output, dropped_tokens)``."""
    dt = cfg.dtype
    h = _rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps, cfg.use_fused_norm)
    if cfg.moe_num_experts:
        y, _, drops = _moe_ffn(lp, h, cfg)
        return x + y, drops
    g = jax.nn.silu(_mm(h, lp, "w_gate", dt)) * _mm(h, lp, "w_up", dt)
    return x + _mm(g, lp, "w_down", dt), jnp.float32(0.0)


def _lm_head(params: Dict, cfg: LlamaConfig, x):
    """Final norm + LM head on the last-position hidden ``x [B, 1, E]`` ->
    fp32 logits ``[B, V]`` (shared by the dense and paged cache paths)."""
    x = _rms_norm(x, params["ln_f"], cfg.rms_norm_eps, cfg.use_fused_norm)
    if cfg.tie_word_embeddings:
        logits = (x @ params["embed"].T.astype(cfg.dtype))[:, 0]
    else:
        logits = _mm(x, params, "lm_head", cfg.dtype)[:, 0]
    return logits.astype(jnp.float32)


def _fwd_cached(params: Dict, cfg: LlamaConfig, ids, cache: Dict, cos, sin,
                kv_mask, write_idx):
    """Embed ``ids [B, T]``, run all layers against the cache (lax.scan over
    the stacked [L, ...] params+cache), return (last-position logits [B, V],
    new cache)."""
    x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)

    def body(h, xs):
        lp, ck, cv = xs
        h, ck, cv, drops = _cached_layer(lp, h, ck, cv, cos, sin, kv_mask,
                                         write_idx, cfg)
        return h, (ck, cv, drops)

    x, (ck, cv, drops) = lax.scan(body, x, (params["layers"], cache["k"],
                                            cache["v"]))
    logits = _lm_head(params, cfg, x[:, -1:])
    return logits, {"k": ck, "v": cv}, drops.sum()


def _row_tables(cfg: LlamaConfig, pos):
    """Per-row RoPE tables for positions ``pos [B, T]`` -> cos/sin [B,T,D]."""
    from ..kernels.rope import rope_cos_sin
    T = pos.shape[1]
    mk = jax.vmap(functools.partial(rope_cos_sin, T, cfg.head_dim,
                                    cfg.rope_theta))
    return mk(position_ids=pos)


def left_align(ids, prompt_lens, pad_token_id: int = 0):
    """Right-padded rows -> left-padded (row b's tokens end at index S-1)."""
    B, S = ids.shape
    shift = (S - prompt_lens)[:, None]
    src = (jnp.arange(S)[None, :] - shift) % S
    out = jnp.take_along_axis(ids, src, axis=1)
    return jnp.where(jnp.arange(S)[None, :] >= shift, out, pad_token_id)


def prefill(params: Dict, cfg: LlamaConfig, ids, prompt_lens, cache: Dict,
            left_padded: bool = False):
    """Run the prompt through the model, filling cache positions [0, S).

    ``ids [B, S]`` is RIGHT-padded ragged (the public convention) unless
    ``left_padded=True``; rows are left-aligned internally so every row's
    last prompt token sits at index S-1 (see module docstring). Returns
    (next-token logits [B, V], cache, dropped_tokens) — the last is the
    in-graph MoE capacity-drop count (0.0 for dense configs; r4 VERDICT
    next #10).
    """
    if not left_padded:
        ids = left_align(ids, prompt_lens)
    B, S = ids.shape
    C = cache["k"].shape[2]
    shift = S - prompt_lens                                  # [B] pad amount
    valid = jnp.arange(S)[None, :] >= shift[:, None]         # [B, S]
    pos = jnp.maximum(jnp.arange(S)[None, :] - shift[:, None], 0)
    cos, sin = _row_tables(cfg, pos)
    causal = jnp.arange(C)[None, :] <= jnp.arange(S)[:, None]  # [S, C]
    valid_k = jnp.pad(valid, ((0, 0), (0, C - S)))             # [B, C]
    kv_mask = causal[None] & valid_k[:, None, :]
    logits, cache, drops = _fwd_cached(params, cfg, ids, cache, cos, sin,
                                       kv_mask, 0)
    return logits, cache, drops


def decode_step(params: Dict, cfg: LlamaConfig, token, t, prompt_lens,
                prompt_pad, cache: Dict):
    """One decode step: ``token [B]`` at step ``t`` (0-based), writing cache
    position ``S + t`` (``prompt_pad = S`` the left-padded prompt length).
    Returns (logits [B, V], cache, dropped_tokens)."""
    C = cache["k"].shape[2]
    pos = (prompt_lens + t)[:, None]                         # [B, 1]
    cos, sin = _row_tables(cfg, pos)
    j = jnp.arange(C)[None, :]
    valid_prompt = (j >= (prompt_pad - prompt_lens)[:, None]) & (j < prompt_pad)
    appended = (j >= prompt_pad) & (j <= prompt_pad + t)
    kv_mask = (valid_prompt | appended)[:, None, :]          # [B, 1, C]
    return _fwd_cached(params, cfg, token[:, None], cache, cos, sin,
                       kv_mask, prompt_pad + t)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def _sample(logits, key, temperature: float, top_k: Optional[int],
            top_p: Optional[float]):
    """Greedy when ``temperature == 0``; else temperature/top-k/top-p
    sampling (static config -> a fixed compiled program per setting)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        top_k = min(top_k, logits.shape[-1])
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        srt = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (the token
        # that crosses the threshold stays in)
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def seed_key(seed: int):
    """The raw uint32[2] PRNG base key for one seed — pure host
    arithmetic (the threefry key packing ``[seed >> 32, seed & 0xffffffff]``),
    so the serving engine can stamp per-request base keys into its slot
    table without a device dispatch per submit. The per-token key for
    sample index ``t`` is ``jax.random.fold_in(seed_key(seed), t)`` —
    a pure function of ``(seed, t)``, which is what makes sampled streams
    reproducible per ``(request, seed)`` across preemption-recompute,
    crash resubmit, cross-replica failover AND speculative verify (the
    verify samples index ``t`` with exactly the key the sequential step
    would have used)."""
    import numpy as np
    s = int(seed)
    return np.array([(s >> 32) & 0xffffffff, s & 0xffffffff], np.uint32)


def validate_sampling(g: "GenerationConfig") -> None:
    """Structured validation of the sampling knobs a serving submit may
    carry — rejects only genuinely unsupported combinations, naming the
    supported surface (the ``ServingEngine.submit`` contract)."""
    import math as _math
    ok = True
    t = g.temperature
    if t is None or not _math.isfinite(float(t)) or float(t) < 0:
        ok = False
    if g.top_k is not None and int(g.top_k) < 1:
        ok = False
    if g.top_p is not None and not (0.0 < float(g.top_p) <= 1.0):
        ok = False
    if not ok:
        raise ValueError(
            f"unsupported sampling config (temperature={g.temperature!r}, "
            f"top_k={g.top_k!r}, top_p={g.top_p!r}); supported knobs: "
            f"temperature >= 0 (0 = greedy argmax), top_k >= 1 or None "
            f"(disabled), top_p in (0, 1] or None (disabled), integer "
            f"seed")


def sample_tokens(logits, keys, temperature, top_k, top_p):
    """Per-row sampling with DEVICE operands — the serving tier's sampler.

    ``logits [B, V]`` fp32; ``keys [B, 2]`` uint32 per-row PRNG keys
    (already folded to the row's sample index); ``temperature [B]`` fp32;
    ``top_k [B]`` int32 (``0`` disables); ``top_p [B]`` fp32 (``1.0``
    disables — and genuinely keeps the full distribution, see below).
    Every knob is a runtime operand, so ONE compiled program serves every
    request mix — the static-arg :func:`_sample` above compiles one
    program per knob setting and stays the dense ``generate()`` tier's
    spelling.

    Rows with ``temperature == 0`` return ``jnp.argmax(logits)`` selected
    through a ``jnp.where`` — BIT-IDENTICAL to the greedy path, so every
    greedy parity oracle (kernel-vs-gather, int8, prefix-hit, resubmit)
    extends unchanged. Boundary semantics match :func:`_sample` exactly:
    top-p keeps the smallest prefix of the sorted distribution whose
    cumulative mass reaches ``p`` (the crossing token stays IN; a token
    whose preceding cumulative mass already equals ``p`` exactly is out),
    and ``top_p=1.0`` keeps every positive-probability token.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # branchless per-row knobs: greedy rows run the sampling math on a
    # safe temperature and are overridden by the final where
    t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / t
    srt = jnp.sort(scaled, axis=-1)[..., ::-1]           # descending
    k = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)   # 0 = disabled
    kth = jnp.take_along_axis(srt, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled < kth, -jnp.inf, scaled)
    # top-p over the top-k-surviving tail (the same composition order as
    # _sample): entries below the kth VALUE drop out of the sorted view
    # first — a value threshold, not a positional cut, so ties at the
    # k-th rank survive into the top-p stage exactly as in _sample
    srt = jnp.where(srt >= kth, srt, -jnp.inf)
    probs = jax.nn.softmax(srt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p = jnp.clip(top_p, 0.0, 1.0)[:, None]
    keep = cum - probs < p
    cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True)
    masked = jnp.where(masked < cutoff, -jnp.inf, masked)
    sampled = jax.vmap(jax.random.categorical)(keys, masked)
    return jnp.where(temperature <= 0.0, greedy,
                     sampled.astype(jnp.int32))


# ---------------------------------------------------------------------------
# generate: prefill + scan decode in ONE compiled program
# ---------------------------------------------------------------------------

def make_generate_fn(cfg: LlamaConfig, *, max_new_tokens: int,
                     temperature: float = 0.0, top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_token_id: Optional[int] = None,
                     pad_token_id: int = 0, return_drops: bool = False):
    """Build ``gen(params, ids [B,S], prompt_lens [B], key) -> tokens
    [B, max_new_tokens]`` — jit it once, every call is one device program.

    ``ids`` may be right-padded; rows are left-aligned internally (see module
    docstring). Rows finish at ``eos_token_id`` and emit ``pad_token_id``
    thereafter.
    """

    def gen(params, ids, prompt_lens, key):
        B, S = ids.shape
        C = S + max_new_tokens
        ids_l = left_align(ids, prompt_lens, pad_token_id)

        cache = init_cache(cfg, B, C)
        logits, cache, drops0 = prefill(params, cfg, ids_l, prompt_lens,
                                        cache, left_padded=True)

        # first token comes from the prefill logits; subsequent tokens from
        # decode steps 0..max_new-2 (eos itself is emitted, pad thereafter)
        key, sub = jax.random.split(key)
        tok0 = _sample(logits, sub, temperature, top_k, top_p)
        done0 = (jnp.zeros((B,), bool) if eos_token_id is None
                 else tok0 == eos_token_id)

        # decode loop: a lax.while_loop (not scan) so the program EXITS as
        # soon as every row has hit eos — a batch that finishes at step k
        # pays k steps, not max_new_tokens (the alive-mask early exit).
        # Greedy outputs are bit-identical to the full-length scan: the
        # output buffer is pre-filled with pad_token_id, which is exactly
        # what the skipped steps would have emitted for all-done rows.
        def body(carry):
            t, tok, cache, done, key, drops, out = carry
            logits, cache, d = decode_step(params, cfg, tok, t, prompt_lens,
                                           jnp.int32(S), cache)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, sub, temperature, top_k, top_p)
            nxt = jnp.where(done, pad_token_id, nxt).astype(ids.dtype)
            ndone = done if eos_token_id is None else \
                done | (nxt == eos_token_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (t + 1, nxt, cache, ndone, key, drops + d, out)

        def cond(carry):
            t, _, _, done, _, _, _ = carry
            return (t < max_new_tokens - 1) & ~done.all()

        if max_new_tokens > 1:
            out0 = jnp.full((B, max_new_tokens), pad_token_id, ids.dtype)
            out0 = lax.dynamic_update_slice(
                out0, tok0[:, None].astype(ids.dtype), (0, 0))
            carry = (jnp.int32(0), tok0.astype(ids.dtype), cache, done0, key,
                     drops0, out0)
            _, _, _, _, _, drops, out = lax.while_loop(cond, body, carry)
        else:
            drops = drops0
            out = tok0[:, None].astype(ids.dtype)
        if return_drops:
            return out, drops
        return out

    return gen


def generate(params: Dict, ids, cfg: LlamaConfig, *, max_new_tokens: int,
             prompt_lens=None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             seed: Optional[int] = None,
             key: Optional[jax.Array] = None):
    """Fixed-batch decode convenience wrapper: jit-cached by (cfg,
    sampling knobs, shapes).

    This is the DENSE-cache tier — every row holds a ``[B, max_seq]`` KV
    cache for its whole lifetime and the batch retires together (with the
    in-graph all-EOS early exit). Serving traffic with mixed lengths,
    shared prefixes, or admission churn belongs on
    ``inference.serving.ServingEngine`` / ``GenerationPredictor.serve``,
    whose ``ServingConfig.prefix_cache`` / ``prefill_chunk`` / ``preempt``
    knobs add paged on-demand KV, automatic prefix caching, and chunked
    prefill while staying bit-identical to this path under greedy
    decoding — this function doubles as that parity oracle in the tests
    and ``bench --serve``.

    Sampling randomness resolves through ``seed`` (default: the
    ``GenerationConfig.seed`` default, 0 — the previously-hardcoded
    ``PRNGKey(0)``); an explicit ``key`` overrides it."""
    ids = jnp.asarray(ids)
    B, S = ids.shape
    if prompt_lens is None:
        prompt_lens = jnp.full((B,), S, jnp.int32)
    else:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    if key is None:
        key = jax.random.PRNGKey(int(seed) if seed is not None
                                 else GenerationConfig.seed)
    fn = _jitted_gen(cfg, max_new_tokens, temperature, top_k, top_p,
                     eos_token_id, pad_token_id)
    return fn(params, ids, prompt_lens, key)


_GEN_CACHE: Dict = {}


def _jitted_gen(cfg: LlamaConfig, max_new_tokens, temperature, top_k, top_p,
                eos_token_id, pad_token_id):
    # LlamaConfig is a plain (unhashable) dataclass; key the jit cache by its
    # full repr + the sampling knobs. jax.jit's own cache handles shapes.
    key = (repr(cfg), max_new_tokens, temperature, top_k, top_p,
           eos_token_id, pad_token_id)
    if key not in _GEN_CACHE:
        fn = make_generate_fn(
            cfg, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id)
        _GEN_CACHE[key] = jax.jit(fn)
    return _GEN_CACHE[key]


# ---------------------------------------------------------------------------
# streaming decode (cache donated across dispatches)
# ---------------------------------------------------------------------------

class DecodeSession:
    """Token-at-a-time decoding for streaming callers (Predictor wiring).

    Two jitted programs — prefill and step — with the cache DONATED on every
    dispatch, so XLA updates it in place instead of allocating a fresh
    [L, B, C, Hk, D] buffer per token.

        sess = DecodeSession(params, cfg, capacity=512)
        logits = sess.prefill(ids, prompt_lens)   # fills the cache
        for _ in range(n):
            tok = logits.argmax(-1)
            logits = sess.step(tok)
    """

    def __init__(self, params: Dict, cfg: LlamaConfig, capacity: int):
        self.params, self.cfg, self.capacity = params, cfg, capacity
        self._cache = None
        self._t = 0

        def _prefill(params, ids, plens, cache):
            return prefill(params, cfg, ids, plens, cache)

        def _step(params, tok, t, plens, ppad, cache):
            return decode_step(params, cfg, tok, t, plens, ppad, cache)

        self._jpre = jax.jit(_prefill, donate_argnums=(3,))
        self._jstep = jax.jit(_step, donate_argnums=(5,))
        self._dropped = None

    def prefill(self, ids, prompt_lens=None):
        ids = jnp.asarray(ids)
        B, S = ids.shape
        if S > self.capacity:
            raise ValueError(f"prompt {S} exceeds capacity {self.capacity}")
        self._plens = (jnp.full((B,), S, jnp.int32) if prompt_lens is None
                       else jnp.asarray(prompt_lens, jnp.int32))
        self._ppad = jnp.int32(S)
        self._t = 0
        cache = init_cache(self.cfg, B, self.capacity)
        logits, self._cache, drops = self._jpre(self.params, ids,
                                                self._plens, cache)
        self._dropped = drops
        return logits

    def step(self, token):
        if self._cache is None:
            raise RuntimeError("call prefill() first")
        if int(self._ppad) + self._t >= self.capacity:
            raise RuntimeError(f"capacity {self.capacity} exhausted")
        logits, self._cache, drops = self._jstep(
            self.params, jnp.asarray(token), jnp.int32(self._t),
            self._plens, self._ppad, self._cache)
        self._dropped = self._dropped + drops
        self._t += 1
        return logits

    @property
    def dropped_tokens(self) -> float:
        """Cumulative in-graph MoE capacity-drop count for this session
        (always 0.0 for dense configs; nonzero means decode may diverge
        from the full-forward oracle — the checkable form of the module
        docstring's MoE caveat; r4 VERDICT next #10)."""
        return float(self._dropped) if self._dropped is not None else 0.0


# ---------------------------------------------------------------------------
# paged KV cache (block-table attention — the serving-engine entry points)
# ---------------------------------------------------------------------------

def validate_tp(cfg: LlamaConfig, tp: int) -> None:
    """Structured validation of a serving tensor-parallel degree against a
    model config (the same error convention as :func:`validate_sampling` /
    ``llama.validate_quant_mode``): the paged pool shards its kv-heads
    axis, so ``tp`` must divide ``num_kv_heads`` — checked HERE, up front,
    instead of failing deep inside ``device_put`` on an indivisible
    ``Hk``. Raised at ``ServingConfig``/engine construction."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tensor-parallel degree must be >= 1 (1 = the "
                         f"single-device engine), got tp={tp}")
    if tp == 1:
        return
    Hk = cfg.kv_heads
    if Hk % tp:
        divisors = [d for d in range(1, Hk + 1) if Hk % d == 0]
        raise ValueError(
            f"tensor-parallel degree tp={tp} does not divide the model's "
            f"num_kv_heads={Hk} (the paged KV pool shards its kv-heads "
            f"axis); supported degrees for this config: {divisors}")


def _merge_heads(o, cfg: LlamaConfig):
    """Flatten attention output ``[B, T, h, D] -> [B, T, h*D]`` for the
    output projection. Under serving tensor parallelism (``cfg.tp_axis``
    set — the engine's shard_map'd programs) ``h`` is the LOCAL head
    slice: all_gather the shards into the full head set first. The gather
    is a pure tiled concatenation — no floating-point addition — so the
    merged tensor is BITWISE the single-device one and the replicated
    wo/FFN/lm-head math downstream stays inside every greedy/seeded
    parity oracle. (A Megatron row-parallel merge — psum of per-shard
    ``wo`` partials — would change fp accumulation order and break
    bit-parity vs TP=1; measured on XLA:CPU.)"""
    if cfg.tp_axis is not None:
        o = lax.all_gather(o, cfg.tp_axis, axis=2, tiled=True)
    B, T = o.shape[:2]
    return o.reshape(B, T, o.shape[2] * o.shape[3])


def _local_heads(cfg: LlamaConfig, pool: Dict) -> Tuple[int, int]:
    """(query heads, kv heads) of the pool VIEW a paged entry point was
    handed. Under shard_map the pool leaf is this shard's ``Hk/tp`` head
    slice, and the GQA group size ``G = H // Hk`` is shard-invariant — so
    the local query-head count follows from the pool shape and the config
    keeps its global head counts (``cfg.head_dim`` stays correct, being
    derived from the UNCHANGED hidden_size / num_attention_heads)."""
    Hk = pool["k"].shape[3]
    return Hk * (cfg.num_attention_heads // cfg.kv_heads), Hk


def paged_pool_specs(pool: Dict, mesh, axis: str = "tp") -> Dict:
    """PartitionSpecs splitting every pool leaf's kv-heads axis over mesh
    ``axis``: K/V ``[L, N, bs, Hk, D]`` and scale ``[L, N, bs, Hk]``
    leaves both shard dim 3, so int8 pools shard k/v and their scale
    planes identically and a shard's scales always describe its own
    blocks. Block ids stay GLOBAL — tables and slot operands replicate,
    only pool bytes split. Indivisible head counts raise the structured
    :func:`~paddle_tpu.distributed.sharding.shard_dim_spec` error naming
    the leaf."""
    from ..distributed.sharding import shard_dim_spec
    return {name: shard_dim_spec(leaf.shape, mesh, axis, dim=3,
                                 name=f"paged_pool.{name}")
            for name, leaf in pool.items()}


def init_paged_pool(cfg: LlamaConfig, num_blocks: int, block_size: int,
                    dtype=None, kv_quant=None, mesh=None,
                    tp_axis: str = "tp") -> Dict:
    """Physical KV block pool ``{"k","v": [L, num_blocks, block_size, Hk,
    D]}`` shared by every sequence the serving engine runs (PagedAttention
    layout): a sequence holds only the blocks its block table points at,
    so HBM scales with tokens actually in flight instead of
    ``max_slots * max_seq``. Physical block 0 is reserved as the NULL
    block — the scatter target for masked lanes (padded prefill positions,
    retired slots) — and is never handed out by the block manager
    (``inference.serving.paged_cache``).

    ``kv_quant="int8"`` stores K/V as int8 with PER-TOKEN-PER-HEAD fp32
    scales alongside (``{"k","v": int8, "k_scale","v_scale": [L, N, bs,
    Hk]}``): each KV entry quantizes independently at write time, so
    incremental decode scatters never re-quantize a block, preemption
    recompute reproduces bit-identical int8 entries, and the prefix cache
    shares quantized blocks exactly like fp ones (content keys hash token
    ids, not bytes). At ~``(D+4)/(4*D)`` the bytes of an fp32 pool this
    multiplies usable blocks at a fixed byte budget ~3.5x — more
    concurrent sequences, more cached prefixes, more preemption headroom.
    Dequantization happens inside the consumers (fused into the Pallas
    kernel's block loads; the XLA gather fallback dequantizes after its
    gather) — a dense fp copy of the pool never exists.

    With ``mesh`` given (a ``tp_mesh`` — serving tensor parallelism,
    ISSUE 12) every leaf is emitted with a ``NamedSharding`` splitting its
    kv-heads axis over ``tp_axis`` (:func:`paged_pool_specs`): each device
    holds ``Hk/tp`` heads of every block, so per-device KV bytes per token
    divide by the TP degree while block ids, tables and the host-side
    block manager stay device-count-agnostic. int8 pools shard k/v and
    their scale planes identically.
    """
    from .llama import KV_QUANT_MODES, validate_quant_mode
    validate_quant_mode(kv_quant, KV_QUANT_MODES, "kv_quant")
    dt = dtype if dtype is not None else cfg.dtype
    shape = (cfg.num_hidden_layers, num_blocks, block_size, cfg.kv_heads,
             cfg.head_dim)
    if kv_quant == "int8":
        pool = {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:-1], jnp.float32),
                "v_scale": jnp.zeros(shape[:-1], jnp.float32)}
    else:
        pool = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if mesh is not None:
        from jax.sharding import NamedSharding
        specs = paged_pool_specs(pool, mesh, tp_axis)
        pool = {n: jax.device_put(a, NamedSharding(mesh, specs[n]))
                for n, a in pool.items()}
    return pool


def paged_pool_block_bytes(cfg: LlamaConfig, block_size: int, dtype=None,
                           kv_quant=None, tp: int = 1) -> int:
    """Bytes ONE physical block costs across all layers (K + V + scales) —
    the capacity-planning arithmetic behind sizing ``num_blocks`` to a
    byte budget (``bench --serve``'s int8-vs-fp and TP capacity rows
    divide a fixed budget by this per layout). ``tp > 1`` returns the
    PER-DEVICE cost of the block under a tensor-parallel pool: each
    device holds ``Hk/tp`` heads of every block, so a fixed per-device
    byte budget backs ``tp`` times the blocks — the per-chip capacity
    multiplier the TP bench row measures."""
    import numpy as _np
    validate_tp(cfg, tp)
    L, bs = cfg.num_hidden_layers, int(block_size)
    Hk, D = cfg.kv_heads // int(tp), cfg.head_dim
    if kv_quant == "int8":
        return L * bs * Hk * (2 * D * 1 + 2 * 4)
    dt = dtype if dtype is not None else cfg.dtype
    return L * bs * Hk * 2 * D * _np.dtype(dt).itemsize


def _kv_quantize(x):
    """Symmetric per-token-per-head int8: ``x [..., Hk, D]`` fp ->
    ``(q int8 [..., Hk, D], scale fp32 [..., Hk])`` with ``x ~= q *
    scale``. Non-finite inputs (a poisoned request's NaN K/V) yield NaN
    scales, so dequantized reads stay NaN — quantization never LAUNDERS
    poison into plausible values; containment stays with the attention
    mask exactly as on fp pools."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _kv_store(p: Dict, phys, off, k, v):
    """Scatter freshly computed ``k``/``v [..., Hk, D]`` into one layer's
    pool slice at ``(phys, off)`` (quantizing when the pool is int8).
    Returns ``(new_pool_layer, k_attend, v_attend)`` — the attend pair is
    what LATER READS of these entries will observe (identity for fp pools,
    the int8 round-trip for quantized ones), so the batched prefill can
    attend exactly the values decode will gather back and every engine
    path sees ONE consistent view of a KV entry."""
    out = dict(p)
    if "k_scale" in p:
        qk, sk = _kv_quantize(k)
        qv, sv = _kv_quantize(v)
        out["k"] = p["k"].at[phys, off].set(qk)
        out["v"] = p["v"].at[phys, off].set(qv)
        out["k_scale"] = p["k_scale"].at[phys, off].set(sk)
        out["v_scale"] = p["v_scale"].at[phys, off].set(sv)
        return out, qk.astype(jnp.float32) * sk[..., None], \
            qv.astype(jnp.float32) * sv[..., None]
    out["k"] = p["k"].at[phys, off].set(k.astype(p["k"].dtype))
    out["v"] = p["v"].at[phys, off].set(v.astype(p["v"].dtype))
    return out, k, v


def _kv_gather(p: Dict, block_tables, B: int, C: int, Hk: int, D: int):
    """Gather one layer's pool through the block tables into logical order
    ``[B, C, Hk, D]``, dequantizing int8 pools after the gather — the XLA
    FALLBACK path (``_masked_sdpa`` consumes the result). The Pallas
    kernel (``kernels.paged_attention``) never materializes this."""
    kk = p["k"][block_tables].reshape(B, C, Hk, D)
    vv = p["v"][block_tables].reshape(B, C, Hk, D)
    if "k_scale" in p:
        ks = p["k_scale"][block_tables].reshape(B, C, Hk)
        vs = p["v_scale"][block_tables].reshape(B, C, Hk)
        kk = kk.astype(jnp.float32) * ks[..., None]
        vv = vv.astype(jnp.float32) * vs[..., None]
    return kk, vv


def _lora_xs(params: Dict, pool: Dict, lora: Optional[Dict]):
    """Scan xs for one paged forward pass: the stacked layer weights and
    the pool, plus — when multi-adapter LoRA serving is on — the stacked
    adapter-pool leaves (``lora["layers"]``, sliced per layer alongside
    the weights; see ``models.lora``). ``lora`` is ``None`` on LoRA-less
    builds, which keeps the traced computation BYTE-IDENTICAL to the
    pre-LoRA engine — the zero-cost-for-base-traffic contract."""
    if lora is None:
        return (params["layers"], pool)
    return (params["layers"], pool, lora["layers"])


def _lora_unpack(xs):
    """(layer params, pool layer, adapter layer or None) from scan xs."""
    if len(xs) == 2:
        lp, pz = xs
        return lp, pz, None
    return xs


def paged_prefill(params: Dict, cfg: LlamaConfig, ids, prompt_lens,
                  block_tables, pool: Dict, active, lora=None):
    """Prefill a BATCH of admitted sequences into the paged pool.

    ``ids [B, Sb]`` right-padded to the (power-of-2 bucketed) length
    ``Sb``; ``prompt_lens [B]`` the real token counts; ``block_tables
    [B, W]`` each row's physical block ids (logical position ``j`` lives
    in block ``table[j // block_size]`` at offset ``j % block_size``);
    ``active [B]`` bool — the admission step pads the batch dim to the
    engine's ``max_slots`` so prefill executables are bounded by the
    BUCKET count alone, and inactive pad rows scatter into the null block.
    Right-padding keeps RoPE positions at the plain ``0..Sb-1`` table and
    the causal mask makes each row's pad tail invisible to its real
    positions; pad-position K/V also scatter into the null block. On int8
    pools the attention reads the QUANTIZED round-trip of this chunk's
    K/V (``_kv_store``'s attend view), so prefill attends exactly the
    values decode/chunk dispatches will later gather — cold and
    prefix-hit requests see one consistent quantized history. ``lora``
    (optional) is the multi-adapter operand ``{"ids": [B] int32 slot
    ids, "layers": stacked adapter pool}`` — a device operand like the
    sampling knobs, so adapter churn never retraces (``models.lora``).
    Returns (next-token logits ``[B, V]`` read at each row's
    ``prompt_len - 1``, pool, dropped_tokens).
    """
    from ..kernels.rope import rope_cos_sin
    B, Sb = ids.shape
    H, Hk = _local_heads(cfg, pool)    # the shard's head slice under TP
    D = cfg.head_dim
    bs = pool["k"].shape[2]
    W = block_tables.shape[1]
    dt = cfg.dtype
    cos, sin = rope_cos_sin(Sb, D, cfg.rope_theta)
    j = jnp.arange(Sb)
    valid = (j[None, :] < prompt_lens[:, None]) & active[:, None]   # [B, Sb]
    phys = jnp.where(valid, block_tables[:, jnp.minimum(j // bs, W - 1)], 0)
    off = jnp.broadcast_to(j % bs, (B, Sb))
    kv_mask = jnp.broadcast_to((j[None, :] <= j[:, None])[None],
                               (B, Sb, Sb))             # causal per row

    x = jnp.take(params["embed"], ids, axis=0).astype(dt)

    def body(h, xs):
        lp, pz, ll = _lora_unpack(xs)
        hh = _rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
        q = _mm(hh, lp, "wq", dt)
        k = _mm(hh, lp, "wk", dt)
        v = _mm(hh, lp, "wv", dt)
        if ll is not None:
            lids = lora["ids"]
            q = q + lora_delta(hh, ll["qA"], ll["qB"], lids, dt)
            k = k + lora_delta(hh, ll["kA"], ll["kB"], lids, dt)
            v = v + lora_delta(hh, ll["vA"], ll["vB"], lids, dt)
        q = q.reshape(B, Sb, H, D)
        k = k.reshape(B, Sb, Hk, D)
        v = v.reshape(B, Sb, Hk, D)
        q = _rope(q, cos, sin, False)
        k = _rope(k, cos, sin, False)
        pz, ka, va = _kv_store(pz, phys, off, k, v)
        o = _masked_sdpa(q, ka, va, kv_mask)
        m = _merge_heads(o, cfg).astype(dt)
        d = _mm(m, lp, "wo", dt)
        if ll is not None:
            d = d + lora_delta(m, ll["oA"], ll["oB"], lora["ids"], dt)
        h = h + d
        h, drops = _ffn_tail(lp, h, cfg)
        return h, (pz, drops)

    x, (pool, drops) = lax.scan(body, x, _lora_xs(params, pool, lora))
    idx = jnp.maximum(prompt_lens - 1, 0)[:, None, None]
    last = jnp.take_along_axis(x, idx, axis=1)          # [B, 1, E]
    return _lm_head(params, cfg, last), pool, drops.sum()


def paged_prefill_chunk(params: Dict, cfg: LlamaConfig, ids, start,
                        chunk_len, block_tables, pool: Dict, lora=None):
    """Prefill-from-offset: one sequence's token chunk against the pool.

    The entry point behind CHUNKED PREFILL and PREFIX-CACHE HITS
    (``inference.serving``): compute KV for positions ``[start, start +
    chunk_len)`` of a single sequence whose earlier positions are already
    in the pool — written by previous chunks, or mapped from the prefix
    cache (the cache-hit block remap is pure host bookkeeping; this kernel
    just attends through the block table it is handed).

    ``ids [1, Sb]`` right-padded chunk tokens (``Sb`` the power-of-2
    bucket); ``start``/``chunk_len`` DEVICE scalars — chunk position and
    real length never retrace; ``block_tables [1, W]`` must cover ``start
    + chunk_len`` KV entries. Queries RoPE at their absolute positions,
    scatter their K/V into the pool, then attend the GATHERED pool
    (``pool[block_tables]``) under the causal mask ``j <= start + i`` —
    exactly the decode step's gather generalized to ``Sb`` queries, so
    cached-prefix KV and freshly-scattered in-chunk KV are read through
    one path. Masked lanes sit at -1e30 -> exact 0.0 in the fp32 softmax
    (see ``_masked_sdpa``), so outputs are bit-identical to the dense
    cache's regardless of the gather width. Returns (next-token logits
    ``[1, V]`` read at position ``start + chunk_len - 1``, pool,
    dropped_tokens).
    """
    B, Sb = ids.shape
    H, Hk = _local_heads(cfg, pool)    # the shard's head slice under TP
    D = cfg.head_dim
    bs = pool["k"].shape[2]
    W = block_tables.shape[1]
    C = W * bs
    dt = cfg.dtype
    j = jnp.arange(Sb)
    pos = start + j[None, :]                             # [1, Sb] absolute
    cos, sin = _row_tables(cfg, pos)
    valid = j[None, :] < chunk_len                       # [1, Sb]
    phys = jnp.where(valid,
                     block_tables[:, jnp.minimum(pos[0] // bs, W - 1)], 0)
    off = pos % bs
    jg = jnp.arange(C)[None, None, :]                    # key positions
    # every position <= the query's is written (previous chunks + cache
    # hits + this chunk's causal prefix); later/pad lanes are masked
    kv_mask = jg <= pos[:, :, None]                      # [1, Sb, C]

    x = jnp.take(params["embed"], ids, axis=0).astype(dt)

    def body(h, xs):
        lp, pz, ll = _lora_unpack(xs)
        hh = _rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
        q = _mm(hh, lp, "wq", dt)
        k = _mm(hh, lp, "wk", dt)
        v = _mm(hh, lp, "wv", dt)
        if ll is not None:
            lids = lora["ids"]
            q = q + lora_delta(hh, ll["qA"], ll["qB"], lids, dt)
            k = k + lora_delta(hh, ll["kA"], ll["kB"], lids, dt)
            v = v + lora_delta(hh, ll["vA"], ll["vB"], lids, dt)
        q = q.reshape(B, Sb, H, D)
        k = k.reshape(B, Sb, Hk, D)
        v = v.reshape(B, Sb, Hk, D)
        q = _rope(q, cos, sin, False)
        k = _rope(k, cos, sin, False)
        pz, _, _ = _kv_store(pz, phys, off, k, v)
        kk, vv = _kv_gather(pz, block_tables, B, C, Hk, D)
        o = _masked_sdpa(q, kk, vv, kv_mask)
        m = _merge_heads(o, cfg).astype(dt)
        d = _mm(m, lp, "wo", dt)
        if ll is not None:
            d = d + lora_delta(m, ll["oA"], ll["oB"], lora["ids"], dt)
        h = h + d
        h, drops = _ffn_tail(lp, h, cfg)
        return h, (pz, drops)

    x, (pool, drops) = lax.scan(body, x, _lora_xs(params, pool, lora))
    idx = jnp.full((B, 1, 1), jnp.maximum(chunk_len - 1, 0))
    last = jnp.take_along_axis(x, idx, axis=1)           # [1, 1, E]
    return _lm_head(params, cfg, last), pool, drops.sum()


def paged_decode_step(params: Dict, cfg: LlamaConfig, tokens, seq_lens,
                      block_tables, pool: Dict, active,
                      use_kernel: bool = False, lora=None):
    """One decode iteration over ``M`` serving slots against the block pool.

    ``tokens [M]`` the last sampled token per slot; ``seq_lens [M]`` the KV
    entries already written (= the new token's position); ``block_tables
    [M, W]``; ``active [M]`` bool — inactive slots (empty, retired, past
    their budget) scatter their K/V into the null block and their logits
    are garbage the scheduler ignores. Attention reads each slot's own
    blocks and masks positions ``> seq_len``, through one of two paths:

    * ``use_kernel=False`` — the XLA gather fallback: ``pool[block_tables]``
      materializes the ``[M, W*bs, Hk, D]`` logical view (dequantized for
      int8 pools), then ``_masked_sdpa`` runs the masked softmax. The
      reference oracle, and the runtime path off-TPU by default.
    * ``use_kernel=True`` — the Pallas flash-decoding kernel
      (:func:`paddle_tpu.kernels.paged_attention`): block tables are
      consumed inside the kernel (each K/V block DMA'd once per kv head,
      int8 dequant fused into the load), split-K over KV blocks with the
      online-softmax merge. No gather is ever materialized — the
      long-context bandwidth win. STATIC: bake it per compiled program
      (``ServingConfig.paged_kernel`` / ``FLAGS_serving_paged_kernel``).

    Returns (logits ``[M, V]``, pool, dropped_tokens).
    """
    M = tokens.shape[0]
    H, Hk = _local_heads(cfg, pool)    # the shard's head slice under TP
    D = cfg.head_dim
    bs = pool["k"].shape[2]
    W = block_tables.shape[1]
    C = W * bs
    dt = cfg.dtype
    cos, sin = _row_tables(cfg, seq_lens[:, None])       # [M, 1, D]
    widx = jnp.minimum(seq_lens // bs, W - 1)
    phys = jnp.where(active,
                     jnp.take_along_axis(block_tables, widx[:, None],
                                         axis=1)[:, 0], 0)
    off = seq_lens % bs
    jj = jnp.arange(C)[None, :]
    kv_mask = (jj <= seq_lens[:, None])[:, None, :]      # [M, 1, C]

    x = jnp.take(params["embed"], tokens[:, None], axis=0).astype(dt)

    def body(h, xs):
        lp, pz, ll = _lora_unpack(xs)
        hh = _rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
        q = _mm(hh, lp, "wq", dt)
        k = _mm(hh, lp, "wk", dt)
        v = _mm(hh, lp, "wv", dt)
        if ll is not None:
            lids = lora["ids"]
            q = q + lora_delta(hh, ll["qA"], ll["qB"], lids, dt)
            k = k + lora_delta(hh, ll["kA"], ll["kB"], lids, dt)
            v = v + lora_delta(hh, ll["vA"], ll["vB"], lids, dt)
        q = q.reshape(M, 1, H, D)
        k = k.reshape(M, 1, Hk, D)
        v = v.reshape(M, 1, Hk, D)
        q = _rope(q, cos, sin, False)
        k = _rope(k, cos, sin, False)
        pz, _, _ = _kv_store(pz, phys, off, k[:, 0], v[:, 0])
        if use_kernel:
            from ..kernels.paged_attention import paged_attention
            o = paged_attention(q[:, 0], pz["k"], pz["v"], block_tables,
                                seq_lens, k_scale=pz.get("k_scale"),
                                v_scale=pz.get("v_scale"))[:, None]
        else:
            kk, vv = _kv_gather(pz, block_tables, M, C, Hk, D)
            o = _masked_sdpa(q, kk, vv, kv_mask)
        m = _merge_heads(o, cfg).astype(dt)
        d = _mm(m, lp, "wo", dt)
        if ll is not None:
            d = d + lora_delta(m, ll["oA"], ll["oB"], lora["ids"], dt)
        h = h + d
        h, drops = _ffn_tail(lp, h, cfg)
        return h, (pz, drops)

    x, (pool, drops) = lax.scan(body, x, _lora_xs(params, pool, lora))
    return _lm_head(params, cfg, x), pool, drops.sum()


def _lm_head_all(params: Dict, cfg: LlamaConfig, x):
    """Final norm + LM head over EVERY position of ``x [B, T, E]`` ->
    fp32 logits ``[B, T, V]`` — the speculative verify needs one
    next-token distribution per drafted position, not just the last."""
    x = _rms_norm(x, params["ln_f"], cfg.rms_norm_eps, cfg.use_fused_norm)
    if cfg.tie_word_embeddings:
        logits = x @ params["embed"].T.astype(cfg.dtype)
    else:
        logits = _mm(x, params, "lm_head", cfg.dtype)
    return logits.astype(jnp.float32)


def paged_spec_step(params: Dict, cfg: LlamaConfig, tokens, seq_lens,
                    draft_lens, block_tables, pool: Dict, active,
                    use_kernel: bool = False, lora=None):
    """Speculative VERIFY over ``M`` serving slots: one multi-query decode
    iteration per slot against the block pool.

    ``tokens [M, Q]`` — row ``m`` holds ``[t0, d1, .., d_k, pad..]``: the
    slot's last sampled token followed by ``draft_lens[m] <= Q - 1``
    drafted tokens (pad lanes repeat a real token — finite by
    construction, and their K/V scatter is masked to the null block);
    ``seq_lens [M]`` — KV entries already committed (= ``t0``'s write
    position, exactly :func:`paged_decode_step`'s contract); ``active
    [M]`` bool. The step writes K/V for positions ``seq_lens + q`` for
    every valid query ``q <= draft_lens`` and returns logits for each:
    ``logits[m, q]`` is the next-token distribution AFTER
    ``tokens[m, :q+1]`` — verifying draft ``d_{q+1}`` against the token
    sampled from ``logits[m, q]`` reproduces the sequential decode stream
    exactly (query ``q`` attends ``j <= seq_lens[m] + q``: committed KV
    plus the in-pass draft prefix, the same set the sequential step at
    that position would see; on int8 pools the attention reads the
    QUANTIZED round-trip of the in-pass writes, exactly like
    :func:`paged_prefill_chunk`).

    The engine rolls back on rejection HOST-SIDE: positions past the
    accepted prefix hold stale draft KV that the next dispatch's write at
    the new ``seq_len`` overwrites (position ``seq_len``) or the
    ``j <= seq_len`` mask hides (beyond), and surplus BLOCKS return to
    the ref-counted manager via the preemption free path. Garbage query
    rows (``q > draft_lens[m]``) attend the CAPPED window ``j <=
    seq_lens + draft_lens`` so the union of attendable positions never
    reaches unwritten block tails — the poison-containment contract
    (``_masked_sdpa``/kernel V-zeroing) extends unchanged.

    ``use_kernel=True`` runs the Pallas flash-decoding kernel's
    multi-query entry point (:func:`paddle_tpu.kernels.paged_attention`
    with ``draft_lens``) — block tables consumed in-kernel, one K/V block
    DMA per kv head scored against all ``Q`` query rows. Returns
    (logits ``[M, Q, V]``, pool, dropped_tokens)."""
    x, pool, drops = _paged_multiquery_forward(
        params, cfg, tokens, seq_lens, draft_lens, block_tables, pool,
        active, use_kernel, lora)
    return _lm_head_all(params, cfg, x), pool, drops


def paged_mixed_step(params: Dict, cfg: LlamaConfig, tokens, starts,
                     q_lens, block_tables, pool: Dict, active,
                     use_kernel: bool = False, lora=None):
    """ONE mixed prefill+decode iteration over ``M`` serving slots: each
    row carries a per-row ROLE through two device operands, so role churn
    (which slots are mid-prefill vs decoding this step) never retraces.

    ``tokens [M, Q]`` — row ``m`` holds ``q_lens[m] <= Q`` real tokens
    (pad lanes repeat a real token; their K/V scatter is masked to the
    null block); ``starts [M]`` — KV entries already committed for the
    row (``num_computed`` for a mid-prefill prompt, ``seq_len`` for a
    decoding slot). A decode slot is the ``q_lens == 1`` degenerate case
    — exactly :func:`paged_decode_step`'s computation; a prefill chunk is
    a ``q_lens == n`` row writing K/V for positions ``[starts, starts +
    n)`` with query ``q`` attending ``j <= starts + q`` — exactly
    :func:`paged_prefill_chunk`'s causal window. Both are the
    ``draft_lens = q_lens - 1`` specialization of the speculative-verify
    forward (:func:`paged_spec_step`), which is what this shares, so the
    kernel's multi-query entry and the gather oracle serve all three
    unchanged.

    Returns ``(logits [M, V], pool, dropped_tokens)`` where ``logits[m]``
    is the next-token distribution after the row's LAST real token — a
    decode slot's next sample, or a prompt-completing chunk's FIRST
    token, sampled in the same dispatch that finished its prefill."""
    draft_lens = jnp.maximum(q_lens - 1, 0)
    x, pool, drops = _paged_multiquery_forward(
        params, cfg, tokens, starts, draft_lens, block_tables, pool,
        active, use_kernel, lora)
    last = jnp.take_along_axis(x, draft_lens[:, None, None], axis=1)
    return _lm_head(params, cfg, last), pool, drops


def _paged_multiquery_forward(params: Dict, cfg: LlamaConfig, tokens,
                              seq_lens, draft_lens, block_tables,
                              pool: Dict, active, use_kernel: bool,
                              lora):
    """The multi-query decode iteration both :func:`paged_spec_step` and
    :func:`paged_mixed_step` are views of: embed ``tokens [M, Q]``, write
    K/V for every valid query position ``seq_lens + q`` (``q <=
    draft_lens``), attend ``j <= seq_lens + min(q, draft_lens)``, and
    return the hidden states ``[M, Q, E]`` (plus pool and MoE drops) —
    the callers differ only in which positions they project to logits."""
    M, Q = tokens.shape
    H, Hk = _local_heads(cfg, pool)    # the shard's head slice under TP
    D = cfg.head_dim
    bs = pool["k"].shape[2]
    W = block_tables.shape[1]
    C = W * bs
    dt = cfg.dtype
    qi = jnp.arange(Q)
    pos = seq_lens[:, None] + qi[None, :]                # [M, Q] absolute
    cos, sin = _row_tables(cfg, pos)
    valid_q = (qi[None, :] <= draft_lens[:, None]) & active[:, None]
    widx = jnp.minimum(pos // bs, W - 1)
    phys = jnp.where(valid_q,
                     jnp.take_along_axis(block_tables, widx, axis=1), 0)
    off = pos % bs
    jj = jnp.arange(C)[None, None, :]
    # query q attends j <= seq_len + min(q, draft_len): its committed KV
    # plus the in-pass draft prefix; garbage rows cap at draft_len so no
    # row's mask ever reaches an unwritten position
    qcap = jnp.minimum(qi[None, :], draft_lens[:, None])  # [M, Q]
    kv_mask = jj <= (seq_lens[:, None] + qcap)[:, :, None]  # [M, Q, C]

    x = jnp.take(params["embed"], tokens, axis=0).astype(dt)

    def body(h, xs):
        lp, pz, ll = _lora_unpack(xs)
        hh = _rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
        q = _mm(hh, lp, "wq", dt)
        k = _mm(hh, lp, "wk", dt)
        v = _mm(hh, lp, "wv", dt)
        if ll is not None:
            lids = lora["ids"]
            q = q + lora_delta(hh, ll["qA"], ll["qB"], lids, dt)
            k = k + lora_delta(hh, ll["kA"], ll["kB"], lids, dt)
            v = v + lora_delta(hh, ll["vA"], ll["vB"], lids, dt)
        q = q.reshape(M, Q, H, D)
        k = k.reshape(M, Q, Hk, D)
        v = v.reshape(M, Q, Hk, D)
        q = _rope(q, cos, sin, False)
        k = _rope(k, cos, sin, False)
        pz, _, _ = _kv_store(pz, phys, off, k, v)
        if use_kernel:
            from ..kernels.paged_attention import paged_attention
            o = paged_attention(q, pz["k"], pz["v"], block_tables,
                                seq_lens, draft_lens=draft_lens,
                                k_scale=pz.get("k_scale"),
                                v_scale=pz.get("v_scale"))
        else:
            kk, vv = _kv_gather(pz, block_tables, M, C, Hk, D)
            o = _masked_sdpa(q, kk, vv, kv_mask)
        m = _merge_heads(o, cfg).astype(dt)
        d = _mm(m, lp, "wo", dt)
        if ll is not None:
            d = d + lora_delta(m, ll["oA"], ll["oB"], lora["ids"], dt)
        h = h + d
        h, drops = _ffn_tail(lp, h, cfg)
        return h, (pz, drops)

    x, (pool, drops) = lax.scan(body, x, _lora_xs(params, pool, lora))
    return x, pool, drops.sum()
