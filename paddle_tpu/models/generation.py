"""Autoregressive generation with a KV cache — TPU decode done the XLA way.

Capability target: the reference ecosystem's ``generate()`` surface
(PaddleNLP ``generation_utils.py`` — greedy / sampling with top-k/top-p,
eos handling, ragged prompt batches; SURVEY §2.6 ecosystem row).

TPU redesign, not a translation:

* **One compiled program.** Prefill + the whole decode loop run inside a
  single ``jax.jit`` — the decode loop is a ``lax.scan`` over token steps, so
  there is no per-token Python dispatch (the reference's per-token Python
  loop is exactly the pattern SURVEY §3.1 warns against on TPU).
* **Static cache layout.** The KV cache is a stacked ``[L, B, C, Hk, D]``
  pytree with a *static* capacity ``C = prompt_len + max_new_tokens``; every
  decode step writes at a uniform scalar index via
  ``lax.dynamic_update_slice`` — no dynamic shapes anywhere, so XLA keeps the
  whole loop on-device and updates the cache in place (buffer reuse inside
  the program; the streaming API additionally donates the cache across
  dispatches).
* **Left-aligned ragged batches.** Ragged prompts are left-padded
  internally: every row's last prompt token then sits at the same index, the
  prefill's final-position logits are a plain ``h[:, -1]`` slice, and decode
  writes land at one scalar index for all rows (a right-padded layout would
  need per-row scatter indices).
* **Streaming tier.** :class:`DecodeSession` exposes prefill/step as two
  jitted functions with the cache DONATED between dispatches, for callers
  that need a token at a time (``inference.Predictor`` wiring, speculative
  clients). Same kernels, same cache layout.

MoE caveat: GShard routing capacity is evaluated per forward call, so a
decode step routes B tokens in isolation while a full no-cache forward
routes B*S jointly — when capacity DROPS occur the two paths can diverge
(both are "correct" MoE inference; drops are a training-throughput knob).
Exact greedy parity with the full-forward oracle therefore holds when no
tokens are dropped, which is the regime inference runs in (per-step load
of B tokens over E experts rarely exceeds capacity).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .llama import LlamaConfig, _mm, _moe_ffn, _rms_norm, _rope

__all__ = ["init_cache", "prefill", "decode_step", "make_generate_fn",
           "generate", "DecodeSession"]


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

def init_cache(cfg: LlamaConfig, batch: int, capacity: int,
               dtype=None) -> Dict:
    """Stacked KV cache ``{"k","v": [L, B, C, Hk, D]}`` (static capacity)."""
    dt = dtype if dtype is not None else cfg.dtype
    shape = (cfg.num_hidden_layers, batch, capacity, cfg.kv_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _cached_layer(lp: Dict, x, ck, cv, cos, sin, kv_mask, write_idx,
                  cfg: LlamaConfig):
    """One decoder block attending against the cache.

    ``x [B, T, E]`` (T = prompt length for prefill, 1 for decode);
    ``ck/cv [B, C, Hk, D]`` this layer's cache; ``kv_mask [B, T, C]`` True
    where query t may attend key position j; ``write_idx`` scalar — the new
    K/V rows are written at cache positions [write_idx, write_idx+T).
    Returns ``(y, ck, cv)``. MoE configs also apply the routed FFN (aux loss
    is irrelevant at inference and dropped).
    """
    B, T, E = x.shape
    H, Hk, D = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = _rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
    q = _mm(h, lp, "wq", dt).reshape(B, T, H, D)
    k = _mm(h, lp, "wk", dt).reshape(B, T, Hk, D)
    v = _mm(h, lp, "wv", dt).reshape(B, T, Hk, D)
    q = _rope(q, cos, sin, False)
    k = _rope(k, cos, sin, False)

    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, write_idx, 0, 0))
    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, write_idx, 0, 0))

    kk, vv = ck, cv
    if Hk != H:                       # GQA: expand kv heads for the einsum
        rep = H // Hk
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bthd,bjhd->bhtj", q.astype(jnp.float32),
                   kk.astype(jnp.float32)) * scale
    s = jnp.where(kv_mask[:, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhtj,bjhd->bthd", p.astype(vv.dtype), vv)
    x = x + _mm(o.reshape(B, T, H * D).astype(dt), lp, "wo", dt)

    h = _rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps, cfg.use_fused_norm)
    if cfg.moe_num_experts:
        y, _, drops = _moe_ffn(lp, h, cfg)
        return x + y, ck, cv, drops
    g = jax.nn.silu(_mm(h, lp, "w_gate", dt)) * _mm(h, lp, "w_up", dt)
    return x + _mm(g, lp, "w_down", dt), ck, cv, jnp.float32(0.0)


def _fwd_cached(params: Dict, cfg: LlamaConfig, ids, cache: Dict, cos, sin,
                kv_mask, write_idx):
    """Embed ``ids [B, T]``, run all layers against the cache (lax.scan over
    the stacked [L, ...] params+cache), return (last-position logits [B, V],
    new cache)."""
    x = jnp.take(params["embed"], ids, axis=0).astype(cfg.dtype)

    def body(h, xs):
        lp, ck, cv = xs
        h, ck, cv, drops = _cached_layer(lp, h, ck, cv, cos, sin, kv_mask,
                                         write_idx, cfg)
        return h, (ck, cv, drops)

    x, (ck, cv, drops) = lax.scan(body, x, (params["layers"], cache["k"],
                                            cache["v"]))
    x = _rms_norm(x[:, -1:], params["ln_f"], cfg.rms_norm_eps,
                  cfg.use_fused_norm)
    if cfg.tie_word_embeddings:
        logits = (x @ params["embed"].T.astype(cfg.dtype))[:, 0]
    else:
        logits = _mm(x, params, "lm_head", cfg.dtype)[:, 0]
    return logits.astype(jnp.float32), {"k": ck, "v": cv}, drops.sum()


def _row_tables(cfg: LlamaConfig, pos):
    """Per-row RoPE tables for positions ``pos [B, T]`` -> cos/sin [B,T,D]."""
    from ..kernels.rope import rope_cos_sin
    T = pos.shape[1]
    mk = jax.vmap(functools.partial(rope_cos_sin, T, cfg.head_dim,
                                    cfg.rope_theta))
    return mk(position_ids=pos)


def left_align(ids, prompt_lens, pad_token_id: int = 0):
    """Right-padded rows -> left-padded (row b's tokens end at index S-1)."""
    B, S = ids.shape
    shift = (S - prompt_lens)[:, None]
    src = (jnp.arange(S)[None, :] - shift) % S
    out = jnp.take_along_axis(ids, src, axis=1)
    return jnp.where(jnp.arange(S)[None, :] >= shift, out, pad_token_id)


def prefill(params: Dict, cfg: LlamaConfig, ids, prompt_lens, cache: Dict,
            left_padded: bool = False):
    """Run the prompt through the model, filling cache positions [0, S).

    ``ids [B, S]`` is RIGHT-padded ragged (the public convention) unless
    ``left_padded=True``; rows are left-aligned internally so every row's
    last prompt token sits at index S-1 (see module docstring). Returns
    (next-token logits [B, V], cache, dropped_tokens) — the last is the
    in-graph MoE capacity-drop count (0.0 for dense configs; r4 VERDICT
    next #10).
    """
    if not left_padded:
        ids = left_align(ids, prompt_lens)
    B, S = ids.shape
    C = cache["k"].shape[2]
    shift = S - prompt_lens                                  # [B] pad amount
    valid = jnp.arange(S)[None, :] >= shift[:, None]         # [B, S]
    pos = jnp.maximum(jnp.arange(S)[None, :] - shift[:, None], 0)
    cos, sin = _row_tables(cfg, pos)
    causal = jnp.arange(C)[None, :] <= jnp.arange(S)[:, None]  # [S, C]
    valid_k = jnp.pad(valid, ((0, 0), (0, C - S)))             # [B, C]
    kv_mask = causal[None] & valid_k[:, None, :]
    logits, cache, drops = _fwd_cached(params, cfg, ids, cache, cos, sin,
                                       kv_mask, 0)
    return logits, cache, drops


def decode_step(params: Dict, cfg: LlamaConfig, token, t, prompt_lens,
                prompt_pad, cache: Dict):
    """One decode step: ``token [B]`` at step ``t`` (0-based), writing cache
    position ``S + t`` (``prompt_pad = S`` the left-padded prompt length).
    Returns (logits [B, V], cache, dropped_tokens)."""
    C = cache["k"].shape[2]
    pos = (prompt_lens + t)[:, None]                         # [B, 1]
    cos, sin = _row_tables(cfg, pos)
    j = jnp.arange(C)[None, :]
    valid_prompt = (j >= (prompt_pad - prompt_lens)[:, None]) & (j < prompt_pad)
    appended = (j >= prompt_pad) & (j <= prompt_pad + t)
    kv_mask = (valid_prompt | appended)[:, None, :]          # [B, 1, C]
    return _fwd_cached(params, cfg, token[:, None], cache, cos, sin,
                       kv_mask, prompt_pad + t)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def _sample(logits, key, temperature: float, top_k: Optional[int],
            top_p: Optional[float]):
    """Greedy when ``temperature == 0``; else temperature/top-k/top-p
    sampling (static config -> a fixed compiled program per setting)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        top_k = min(top_k, logits.shape[-1])
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        srt = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (the token
        # that crosses the threshold stays in)
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


# ---------------------------------------------------------------------------
# generate: prefill + scan decode in ONE compiled program
# ---------------------------------------------------------------------------

def make_generate_fn(cfg: LlamaConfig, *, max_new_tokens: int,
                     temperature: float = 0.0, top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_token_id: Optional[int] = None,
                     pad_token_id: int = 0, return_drops: bool = False):
    """Build ``gen(params, ids [B,S], prompt_lens [B], key) -> tokens
    [B, max_new_tokens]`` — jit it once, every call is one device program.

    ``ids`` may be right-padded; rows are left-aligned internally (see module
    docstring). Rows finish at ``eos_token_id`` and emit ``pad_token_id``
    thereafter.
    """

    def gen(params, ids, prompt_lens, key):
        B, S = ids.shape
        C = S + max_new_tokens
        ids_l = left_align(ids, prompt_lens, pad_token_id)

        cache = init_cache(cfg, B, C)
        logits, cache, drops0 = prefill(params, cfg, ids_l, prompt_lens,
                                        cache, left_padded=True)

        # first token comes from the prefill logits; subsequent tokens from
        # decode steps 0..max_new-2 (eos itself is emitted, pad thereafter)
        key, sub = jax.random.split(key)
        tok0 = _sample(logits, sub, temperature, top_k, top_p)
        done0 = (jnp.zeros((B,), bool) if eos_token_id is None
                 else tok0 == eos_token_id)

        def body(carry, t):
            tok, cache, done, key, drops = carry
            logits, cache, d = decode_step(params, cfg, tok, t, prompt_lens,
                                           jnp.int32(S), cache)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, sub, temperature, top_k, top_p)
            nxt = jnp.where(done, pad_token_id, nxt)
            ndone = done if eos_token_id is None else \
                done | (nxt == eos_token_id)
            return (nxt.astype(ids.dtype), cache, ndone, key, drops + d), \
                nxt.astype(ids.dtype)

        if max_new_tokens > 1:
            carry = (tok0.astype(ids.dtype), cache, done0, key, drops0)
            (_, _, _, _, drops), rest = lax.scan(
                body, carry, jnp.arange(max_new_tokens - 1))
            out = jnp.concatenate([tok0[:, None].astype(ids.dtype),
                                   rest.T], axis=1)
        else:
            drops = drops0
            out = tok0[:, None].astype(ids.dtype)
        if return_drops:
            return out, drops
        return out

    return gen


def generate(params: Dict, ids, cfg: LlamaConfig, *, max_new_tokens: int,
             prompt_lens=None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             key: Optional[jax.Array] = None):
    """Convenience wrapper: jit-cached by (cfg, sampling knobs, shapes)."""
    ids = jnp.asarray(ids)
    B, S = ids.shape
    if prompt_lens is None:
        prompt_lens = jnp.full((B,), S, jnp.int32)
    else:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    if key is None:
        key = jax.random.PRNGKey(0)
    fn = _jitted_gen(cfg, max_new_tokens, temperature, top_k, top_p,
                     eos_token_id, pad_token_id)
    return fn(params, ids, prompt_lens, key)


_GEN_CACHE: Dict = {}


def _jitted_gen(cfg: LlamaConfig, max_new_tokens, temperature, top_k, top_p,
                eos_token_id, pad_token_id):
    # LlamaConfig is a plain (unhashable) dataclass; key the jit cache by its
    # full repr + the sampling knobs. jax.jit's own cache handles shapes.
    key = (repr(cfg), max_new_tokens, temperature, top_k, top_p,
           eos_token_id, pad_token_id)
    if key not in _GEN_CACHE:
        fn = make_generate_fn(
            cfg, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id)
        _GEN_CACHE[key] = jax.jit(fn)
    return _GEN_CACHE[key]


# ---------------------------------------------------------------------------
# streaming decode (cache donated across dispatches)
# ---------------------------------------------------------------------------

class DecodeSession:
    """Token-at-a-time decoding for streaming callers (Predictor wiring).

    Two jitted programs — prefill and step — with the cache DONATED on every
    dispatch, so XLA updates it in place instead of allocating a fresh
    [L, B, C, Hk, D] buffer per token.

        sess = DecodeSession(params, cfg, capacity=512)
        logits = sess.prefill(ids, prompt_lens)   # fills the cache
        for _ in range(n):
            tok = logits.argmax(-1)
            logits = sess.step(tok)
    """

    def __init__(self, params: Dict, cfg: LlamaConfig, capacity: int):
        self.params, self.cfg, self.capacity = params, cfg, capacity
        self._cache = None
        self._t = 0

        def _prefill(params, ids, plens, cache):
            return prefill(params, cfg, ids, plens, cache)

        def _step(params, tok, t, plens, ppad, cache):
            return decode_step(params, cfg, tok, t, plens, ppad, cache)

        self._jpre = jax.jit(_prefill, donate_argnums=(3,))
        self._jstep = jax.jit(_step, donate_argnums=(5,))
        self._dropped = None

    def prefill(self, ids, prompt_lens=None):
        ids = jnp.asarray(ids)
        B, S = ids.shape
        if S > self.capacity:
            raise ValueError(f"prompt {S} exceeds capacity {self.capacity}")
        self._plens = (jnp.full((B,), S, jnp.int32) if prompt_lens is None
                       else jnp.asarray(prompt_lens, jnp.int32))
        self._ppad = jnp.int32(S)
        self._t = 0
        cache = init_cache(self.cfg, B, self.capacity)
        logits, self._cache, drops = self._jpre(self.params, ids,
                                                self._plens, cache)
        self._dropped = drops
        return logits

    def step(self, token):
        if self._cache is None:
            raise RuntimeError("call prefill() first")
        if int(self._ppad) + self._t >= self.capacity:
            raise RuntimeError(f"capacity {self.capacity} exhausted")
        logits, self._cache, drops = self._jstep(
            self.params, jnp.asarray(token), jnp.int32(self._t),
            self._plens, self._ppad, self._cache)
        self._dropped = self._dropped + drops
        self._t += 1
        return logits

    @property
    def dropped_tokens(self) -> float:
        """Cumulative in-graph MoE capacity-drop count for this session
        (always 0.0 for dense configs; nonzero means decode may diverge
        from the full-forward oracle — the checkable form of the module
        docstring's MoE caveat; r4 VERDICT next #10)."""
        return float(self._dropped) if self._dropped is not None else 0.0
