"""LLaMA-family decoder — the flagship model.

Capability target: the reference's LLaMA implementation lives in PaddleNLP
(``paddlenlp/transformers/llama/modeling.py``, built from the fleet mpu layers —
SURVEY §2.5 TP/MP and §2.6 ecosystem rows); the hybrid-parallel pretrain of this
model is the reference's headline benchmark (BASELINE.md north star).

TPU redesign, not a translation:

* **Pure-functional core** — ``init_params`` / ``forward`` / ``loss_fn`` operate
  on a plain pytree. Per-layer weights are STACKED on a leading ``[L, ...]`` dim
  and the depth loop is a ``lax.scan``: one trace + one compile regardless of
  depth, and the stacked layout is exactly what the compiled pipeline schedule
  (``distributed.pipeline.pipeline_scan``) consumes.
* **Sharding by annotation** — ``param_specs``/``batch_spec`` return
  ``PartitionSpec`` pytrees (Megatron layout over the ``mp`` axis, optional
  ZeRO-3-style extra sharding over the ``sharding`` axis); GSPMD inserts the
  collectives the reference writes by hand in ``mp_layers.py``.
* **Kernel path** — ``use_kernels=True`` routes RMSNorm/RoPE/attention through
  the Pallas kernels (``paddle_tpu.kernels``); the jnp reference path is the
  numerics oracle and the GSPMD-partitionable fallback.
* **Eager wrapper** — :class:`LlamaForCausalLM` exposes the same network as a
  ``nn.Layer`` for the imperative / ``to_static`` API surface.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["LlamaConfig", "init_params", "forward", "loss_fn", "param_specs",
           "batch_spec", "make_train_step", "LlamaForCausalLM", "num_params",
           "make_pp_train_step", "to_pp_layout", "from_pp_layout",
           "pp_param_specs", "serving_param_specs", "shard_serving_params"]


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5504
    num_hidden_layers: int = 16
    num_attention_heads: int = 16
    num_key_value_heads: Optional[int] = None   # None -> MHA
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_kernels: bool = False        # Pallas flash attention (the big win)
    use_fused_norm: bool = False     # Pallas rms_norm/rope kernels; OFF by
    # default: measured on v5e, XLA's own fusion beats them ~1.4-1.7x for
    # these bandwidth-bound elementwise ops (they exist for API parity with
    # the reference's fused_rms_norm/fused_rope)
    dtype: Any = jnp.float32         # activation/compute dtype
    param_dtype: Any = jnp.float32   # storage dtype
    remat: bool = False              # jax.checkpoint each decoder layer
    remat_policy: Optional[str] = None  # None = full remat; "dots" saves MXU
    # outputs and recomputes only elementwise (less recompute FLOPs, more
    # HBM); "nothing" saves nothing (alias of full remat, explicit)
    sep_axis: Optional[str] = None   # context-parallel mesh axis (e.g. "sep")
    cp_impl: str = "ring"            # "ring" | "ulysses" attention over sep
    # MoE (LLaMA-MoE / Mixtral-style; ref: PaddleNLP MoE models over
    # incubate/distributed/models/moe): > 0 replaces every dense SwiGLU FFN
    # with moe_num_experts GShard-routed experts. Expert weights carry a
    # leading [E] dim sharded over `ep_axis` in param_specs.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    ep_axis: Optional[str] = None    # expert-parallel mesh axis (e.g. "ep")
    tp_axis: Optional[str] = None    # serving tensor-parallel mesh axis
    # (inference.serving ISSUE 12). Set only on the LOCAL config the
    # serving engine's shard_map'd programs close over: the paged decode/
    # prefill/verify entry points then all_gather their attention-output
    # head slices over this axis before the (replicated) output
    # projection. Head counts stay GLOBAL here — the paged entry points
    # derive the local head counts from the pool shard they are handed.
    # User-facing configs leave it None.
    ce_chunks: int = 1               # >1: token-chunked cross-entropy — the
    # fp32 [T, V] logits (2.1GB at the bench config) never materialize;
    # each chunk's logits are recomputed in backward (jax.checkpoint), which
    # frees the HBM that lets remat_policy="save_flash" fit at fp32 Adam
    # (measured roofline, BASELINE.md)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self) -> int:
        return self.num_key_value_heads or self.num_attention_heads


def num_params(cfg: LlamaConfig) -> int:
    E, I, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_hidden_layers)
    kvd = cfg.kv_heads * cfg.head_dim
    ffn = 3 * E * I
    gate = 0
    if cfg.moe_num_experts:
        ffn = cfg.moe_num_experts * 3 * E * I
        gate = E * cfg.moe_num_experts
    per_layer = E * E + 2 * E * kvd + E * E + ffn + gate + 2 * E
    n = V * E + L * per_layer + E
    if not cfg.tie_word_embeddings:
        n += E * V
    return n


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict:
    """Stacked-[L, ...] parameter pytree (truncated-normal / scaled init)."""
    E, I, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                  cfg.num_hidden_layers)
    D = cfg.head_dim
    H, Hk = cfg.num_attention_heads, cfg.kv_heads
    ks = jax.random.split(key, 10)
    pd = cfg.param_dtype

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) /
                math.sqrt(fan_in)).astype(pd)

    Ex = cfg.moe_num_experts
    ffn_shape = ((L, Ex, E, I) if Ex else (L, E, I))
    ffn_dshape = ((L, Ex, I, E) if Ex else (L, I, E))
    params = {
        "embed": dense(ks[0], (V, E), E),
        "layers": {
            "wq": dense(ks[1], (L, E, H * D), E),
            "wk": dense(ks[2], (L, E, Hk * D), E),
            "wv": dense(ks[3], (L, E, Hk * D), E),
            "wo": dense(ks[4], (L, H * D, E), H * D),
            "w_gate": dense(ks[5], ffn_shape, E),
            "w_up": dense(ks[6], ffn_shape, E),
            "w_down": dense(ks[7], ffn_dshape, I),
            "ln_attn": jnp.ones((L, E), pd),
            "ln_mlp": jnp.ones((L, E), pd),
        },
        "ln_f": jnp.ones((E,), pd),
    }
    if Ex:
        params["layers"]["moe_gate"] = dense(ks[9], (L, E, Ex), E)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(ks[8], (E, V), E)
    return params


def param_specs(cfg: LlamaConfig, mp_axis: Optional[str] = "mp",
                fsdp_axis: Optional[str] = None) -> Dict:
    """Megatron-layout PartitionSpecs for the stacked param pytree.

    ``mp_axis`` shards attention heads / ffn intermediate dim (TP);
    ``fsdp_axis`` additionally shards the other matmul dim (ZeRO-3 layout over
    the ``sharding`` axis — ref: GroupShardedStage3, here just a layout).
    """
    mp, fs = mp_axis, fsdp_axis
    ep = cfg.ep_axis
    if cfg.moe_num_experts:
        # experts sharded over ep (E/ep per device); the FFN contraction
        # dims may still carry mp/fs on top (composable hybrid layout)
        ffn_in = P(None, ep, fs, mp)
        ffn_out = P(None, ep, mp, fs)
    else:
        ffn_in = P(None, fs, mp)
        ffn_out = P(None, mp, fs)
    specs = {
        "embed": P(mp, fs),                  # vocab-sharded (VocabParallelEmbedding)
        "layers": {
            "wq": P(None, fs, mp),           # column-parallel
            "wk": P(None, fs, mp),
            "wv": P(None, fs, mp),
            "wo": P(None, mp, fs),           # row-parallel
            "w_gate": ffn_in,
            "w_up": ffn_in,
            "w_down": ffn_out,
            "ln_attn": P(None, None),
            "ln_mlp": P(None, None),
        },
        "ln_f": P(None),
    }
    if cfg.moe_num_experts:
        specs["layers"]["moe_gate"] = P(None, None, None)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(fs, mp)         # vocab-sharded logits
    return specs


def batch_spec(dp_axes=("dp",), sep_axis: Optional[str] = None) -> P:
    """[B, S] token batches: batch over the data axes, seq over sep (CP)."""
    return P(tuple(a for a in dp_axes if a), sep_axis)


def shard_params(params, mesh: Mesh, cfg: LlamaConfig, mp_axis="mp",
                 fsdp_axis=None):
    specs = param_specs(cfg, mp_axis, fsdp_axis)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)


# QKV projections (and their weight-only-int8 scale leaves) are the only
# params the SERVING tensor-parallel layout shards — on the head output dim,
# so each shard computes exactly the q/k/v head slice whose KV pool shard it
# owns. Everything else stays replicated: see serving_param_specs.
_SERVING_TP_SHARDED = ("wq", "wk", "wv", "wq_s", "wk_s", "wv_s")


def serving_param_specs(params: Dict, mesh: Mesh, axis: str = "tp") -> Dict:
    """PartitionSpecs for the serving engine's tensor-parallel layout
    (inference.serving ISSUE 12): ``wq``/``wk``/``wv`` (and their int8
    ``*_s`` scale leaves) COLUMN-sharded on their head output dim over
    ``axis``; every other leaf — ``wo``, the FFN, norms, embed, lm_head —
    REPLICATED.

    This is deliberately NOT the Megatron training layout
    (:func:`param_specs`): attention is head-sharded (each shard runs the
    unmodified kernel on its kv-head slice of the paged pool) and the
    per-shard outputs are merged by an exact all_gather concatenation, so
    the replicated post-attention math is BITWISE the single-device
    engine's — the parity oracle every serving test pins. Row-parallel
    ``wo``/FFN partial sums merged by psum would change the fp
    accumulation order and break bit-parity vs TP=1 (measured on XLA:CPU),
    for an FFN-flops saving the decode hot path doesn't need; the capacity
    win lives in the sharded KV pool. Divisibility failures raise the
    structured :func:`~paddle_tpu.distributed.sharding.shard_dim_spec`
    error naming the offending leaf.
    """
    from ..distributed.sharding import shard_dim_spec

    def leaf_spec(name: str, leaf) -> P:
        if name in _SERVING_TP_SHARDED:
            return shard_dim_spec(leaf.shape, mesh, axis, dim=-1,
                                  name=f"params.layers.{name}")
        return P()

    specs: Dict = {}
    for key, val in params.items():
        if key == "layers":
            specs[key] = {n: leaf_spec(n, a) for n, a in val.items()}
        else:
            specs[key] = jax.tree_util.tree_map(lambda _: P(), val)
    return specs


def shard_serving_params(params: Dict, mesh: Mesh, axis: str = "tp") -> Dict:
    """Lay the (fp or weight-only-int8) param pytree out for serving
    tensor parallelism — the ONE helper behind which dense weights are
    replicated-or-sharded (:func:`serving_param_specs`); the engine, the
    supervisor's rebuild path and every router replica place params
    through here, so a recovered engine can never diverge in layout."""
    specs = serving_param_specs(params, mesh, axis)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _remat_policy(name: Optional[str]):
    """Map a config string to a jax.checkpoint policy (SURVEY §6: the remat
    policy sweep is a first-class MFU knob — full remat recomputes the whole
    block including its matmuls; "dots" keeps MXU outputs in HBM and only
    recomputes the cheap elementwise tail)."""
    if name is None or name == "nothing":
        return None
    import jax.ad_checkpoint as adc
    policies = {
        "dots": adc.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": adc.checkpoint_policies.dots_saveable,
        # save the attention block's outputs ([B,S,E]-sized — cheap in HBM)
        # so backward never re-runs the flash kernel forward; the FFN (whose
        # [B,S,I] intermediates dominate activation memory) still remats.
        # NOTE (measured, v5e): "attn_out" alone does NOT stop the flash
        # fwd re-run — the kernel's bwd needs its lse residual too, which
        # only "save_flash" keeps (names emitted inside the kernel's vjp).
        "save_attn": adc.checkpoint_policies.save_only_these_names(
            "attn_out"),
        "save_qkv_attn": adc.checkpoint_policies.save_only_these_names(
            "attn_out", "qk", "v_proj"),
        # the winning family on the headline config: save the flash kernel's
        # (out, lse) residuals + post-rope q/k (+v), so backward feeds the
        # bwd kernels directly and recompute covers only norms + matmuls
        "save_flash": adc.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "qk", "v_proj"),
        # v is ONE cheap matmul to recompute but 0.77GB to keep (12 layers,
        # bench shapes) — dropping it is what fits fp32-Adam in HBM
        "save_flash_qk": adc.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "qk"),
        "save_flash_only": adc.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"),
    }
    if name not in policies:
        raise ValueError(f"unknown remat_policy {name!r}; "
                         f"options: {sorted(policies)} or None")
    return policies[name]


def _rms_norm(x, w, eps, use_kernels):
    if use_kernels:
        from ..kernels.rms_norm import rms_norm as fused
        return fused(x, w, eps)
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * w.astype(jnp.float32)).astype(x.dtype)


def _rope(x, cos, sin, use_kernels):
    if use_kernels and cos.ndim == 2:
        from ..kernels.rope import apply_rope
        return apply_rope(x, cos, sin)
    # x: [B, S, H, D]; cos/sin: [S, D] or [B, S, D] (per-row positions for
    # packed sequences — the kernel path handles the shared-table case only)
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    expand = (lambda t: t[None, :, None, :]) if cos.ndim == 2 \
        else (lambda t: t[:, :, None, :])
    c = expand(cos).astype(x.dtype)
    s = expand(sin).astype(x.dtype)
    return x * c + rot * s


def _attention(q, k, v, cfg: LlamaConfig, segment_ids=None):
    """Causal self-attention on [B, S, H(k), D]; ``segment_ids [B, S]``
    confines attention within packed sequences (varlen)."""
    if cfg.sep_axis is not None:
        if segment_ids is not None:
            raise NotImplementedError(
                "packed-sequence masking under sep context parallelism is "
                "not supported yet (the ring schedule assumes a plain causal "
                "mask)")
        # context parallelism: seq stays sharded over the sep axis; ring or
        # Ulysses attention as an explicit shard_map region inside the
        # compiled program (composes with dp GSPMD; mp must be 1 here)
        from jax.sharding import PartitionSpec as P
        from ..core.jax_compat import shard_map
        from ..distributed.context_parallel import (ring_flash_attention,
                                                    ulysses_attention)
        from ..distributed.topology import get_hybrid_communicate_group
        Hk, H = k.shape[2], q.shape[2]
        if Hk != H:  # ring/ulysses paths expect matched heads; expand GQA
            rep = H // Hk
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        fn = ring_flash_attention if cfg.cp_impl == "ring" \
            else ulysses_attention
        mesh = get_hybrid_communicate_group().mesh
        spec = P(None, cfg.sep_axis, None, None)
        region = shard_map(
            lambda a, b, c: fn(a, b, c, cfg.sep_axis, True, cfg.use_kernels),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return region(q, k, v)
    if cfg.use_kernels:
        from ..kernels.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=True,
                               segment_ids=segment_ids)
    B, S, H, D = q.shape
    Hk = k.shape[2]
    if Hk != H:  # GQA: expand kv heads
        rep = H // Hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids)
        mask = mask & (seg[:, None, :, None] == seg[:, None, None, :])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if segment_ids is not None:  # rows with no visible keys output 0
        p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o.astype(q.dtype)


def _masked_sdpa(q, kk, vv, kv_mask):
    """Decode-path attention over an explicit KV set: ``q [B, T, H, D]``
    against ``kk/vv [B, C, Hk, D]`` with ``kv_mask [B, T, C]`` (True =
    query t may attend key j). fp32 scores, GQA kv-head expansion, masked
    positions at -1e30 (exp underflows to an exact 0.0 in the softmax, so
    enlarging C with masked slots never changes the attended values).
    Shared by the dense KV cache and the paged block cache
    (:mod:`paddle_tpu.models.generation`)."""
    H, Hk = q.shape[2], kk.shape[2]
    # V at positions NO query may attend (the paged null block, stale KV
    # in a reused block's tail) must be zeroed, not merely zero-WEIGHTED:
    # a poisoned request can park non-finite KV there (e.g. out-of-vocab
    # ids -> NaN embeddings scattered through a masked lane), and
    # 0 * NaN = NaN would wipe every other sequence's row. For finite KV
    # the masked contribution was already an exact 0.0, so this select is
    # bit-invisible; K needs nothing — a NaN score at a masked position
    # is replaced by the -1e30 where below.
    pos_valid = kv_mask.any(axis=1)   # [B, C]: attendable by some query
    vv = jnp.where(pos_valid[:, :, None, None], vv, 0)
    if Hk != H:                       # GQA: expand kv heads for the einsum
        rep = H // Hk
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bthd,bjhd->bhtj", q.astype(jnp.float32),
                   kk.astype(jnp.float32)) * scale
    s = jnp.where(kv_mask[:, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhtj,bjhd->bthd", p.astype(vv.dtype), vv)


def _moe_ffn(lp: Dict, h, cfg: LlamaConfig):
    """GShard-routed SwiGLU experts on ``h [B, S, E]`` -> (out, aux_loss).

    Expert weights carry a leading [E_experts] dim (sharded over
    ``cfg.ep_axis`` by :func:`param_specs`); the dispatch/combine einsums
    are the dense GShard formulation, so GSPMD inserts the all_to_all the
    reference writes by hand (ref: PaddleNLP MoE decoder over
    incubate/distributed/models/moe)."""
    from ..distributed.moe import gshard_routing
    B, S, M = h.shape
    T = B * S
    Ex = cfg.moe_num_experts
    cap = max(1, math.ceil(T * cfg.moe_capacity_factor * cfg.moe_top_k / Ex))
    h2 = h.reshape(T, M)
    # router in fp32: bf16 logits make near-tied top-k selections noisy
    # (the reference's gates also project in fp32); [T,M]x[M,E] is cheap
    logits = h2.astype(jnp.float32) @ lp["moe_gate"].astype(jnp.float32)
    combine, dispatch, aux = gshard_routing(logits, cfg.moe_top_k, cap)
    # in-graph drop counter (r4 VERDICT weak #7 / next #10): every (token,
    # choice) pair that overflowed its expert's capacity queue. Zero in the
    # regimes the docstring's parity claim covers — and now checkable.
    dropped = (jnp.float32(T * cfg.moe_top_k)
               - dispatch.astype(jnp.float32).sum())
    einp = jnp.einsum("tec,tm->ecm", dispatch.astype(h2.dtype), h2)

    def one_expert(wg, wu, wd, xe):
        g = jax.nn.silu(xe @ wg.astype(xe.dtype)) * (xe @ wu.astype(xe.dtype))
        return g @ wd.astype(xe.dtype)

    eout = jax.vmap(one_expert)(lp["w_gate"], lp["w_up"], lp["w_down"], einp)
    y = jnp.einsum("tec,ecm->tm", combine.astype(h2.dtype), eout)
    return y.reshape(B, S, M), aux, dropped


def _mm(h, lp, name, dt):
    """Weight matmul with the optional weight-only-int8 path (r5, VERDICT
    r4 next #6b): when ``quantize_params`` has replaced ``lp[name]`` with
    int8 and added ``lp[name + "_s"]`` scales, route through the Pallas
    stream-dequant kernel on TPU (HBM reads stay int8 — the decode win) /
    an XLA dequant-matmul elsewhere; otherwise the plain bf16 matmul."""
    w = lp[name]
    s = lp.get(name + "_s")
    if s is None:
        return h @ w.astype(dt)
    lead = h.shape[:-1]
    h2 = h.reshape(-1, h.shape[-1])
    from ..kernels.dispatch import on_tpu
    if on_tpu():
        from ..kernels.quant_matmul import weight_only_matmul
        out = weight_only_matmul(h2, w, s, out_dtype=dt)
    else:
        out = h2 @ (w.astype(dt) * s.astype(dt)[None, :])
    return out.reshape(lead + (w.shape[-1],)).astype(dt)


def quantize_params(params: Dict) -> Dict:
    """Per-output-channel symmetric int8 quantization of every dense
    projection ([L, K, N] stacked layer weights + lm_head); scales join
    the pytree as ``<name>_s`` leaves so the scan threads them alongside
    (ref capability: paddle.nn.quant weight_only path / Paddle Inference
    int8; the embed stays fp — it is a gather, not a matmul)."""
    from ..kernels.quant_matmul import quantize_weights
    qp = dict(params)
    layers = dict(params["layers"])
    for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        if name not in layers:
            continue
        w = layers[name]                       # [L, K, N]
        q, s = jax.vmap(quantize_weights)(w)   # [L, K, N] i8, [L, N]
        layers[name] = q
        layers[name + "_s"] = s
        qp["layers"] = layers
    if "lm_head" in params:
        q, s = quantize_weights(params["lm_head"])
        qp["lm_head"] = q
        qp["lm_head_s"] = s
    return qp


QUANTIZE_MODES = (None, "int8")     # weight-only (ensure_quantized)
KV_QUANT_MODES = (None, "int8")     # paged KV-cache pools (generation.
#                                     init_paged_pool / ServingConfig.
#                                     kv_quant). Orthogonal to the weight
#                                     modes: quantize="int8" (weights) and
#                                     kv_quant="int8" (KV blocks) COMPOSE —
#                                     int8 weight streaming + int8 KV pools
#                                     on one engine.


def validate_quant_mode(mode, modes, what: str = "quantize"):
    """The one unknown-quantize-mode error: a structured ValueError naming
    the supported modes (never a bare KeyError/assert), shared by the
    weight-only path (:func:`ensure_quantized`), the KV-pool path
    (``generation.init_paged_pool``) and the serving config."""
    if mode not in modes:
        raise ValueError(f"unknown {what} mode {mode!r}; "
                         f"options: {modes}")
    return mode


def ensure_quantized(params: Dict, mode) -> Dict:
    """Validate a weight-only quantize mode and make the pytree match it:
    ``None`` returns ``params`` untouched, ``"int8"`` runs
    :func:`quantize_params` unless the tree already carries the scale
    leaves (``wq_s``). The one place the accepted-modes list and the
    already-quantized marker live — every decode tier (predictor, serving
    engine) resolves through here. KV-cache quantization is a separate,
    composable knob (:data:`KV_QUANT_MODES`)."""
    validate_quant_mode(mode, QUANTIZE_MODES)
    if mode == "int8" and "wq_s" not in params.get("layers", {}):
        return quantize_params(params)
    return params


def decoder_layer(lp: Dict, x, cos, sin, cfg: LlamaConfig,
                  segment_ids=None):
    """One pre-norm decoder block on un-stacked layer params ``lp``.

    Dense configs return the block output; MoE configs
    (``cfg.moe_num_experts > 0``) return ``(output, aux_loss)``."""
    B, S, E = x.shape
    H, Hk, D = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    dt = cfg.dtype

    from jax.ad_checkpoint import checkpoint_name
    h = _rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps, cfg.use_fused_norm)
    q = _mm(h, lp, "wq", dt).reshape(B, S, H, D)
    k = _mm(h, lp, "wk", dt).reshape(B, S, Hk, D)
    v = _mm(h, lp, "wv", dt).reshape(B, S, Hk, D)
    q = checkpoint_name(_rope(q, cos, sin, cfg.use_fused_norm), "qk")
    k = checkpoint_name(_rope(k, cos, sin, cfg.use_fused_norm), "qk")
    v = checkpoint_name(v, "v_proj")
    o = _attention(q, k, v, cfg, segment_ids).reshape(B, S, H * D)
    o = checkpoint_name(o, "attn_out")
    x = x + _mm(o, lp, "wo", dt)

    h = _rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps, cfg.use_fused_norm)
    if cfg.moe_num_experts:
        y, aux, _drops = _moe_ffn(lp, h, cfg)
        return x + y, aux
    g = jax.nn.silu(_mm(h, lp, "w_gate", dt)) * _mm(h, lp, "w_up", dt)
    return x + _mm(g, lp, "w_down", dt)


def forward(params: Dict, input_ids, cfg: LlamaConfig, segment_ids=None,
            position_ids=None, return_aux: bool = False,
            return_hidden: bool = False):
    """``input_ids [B, S] -> logits [B, S, V]`` (single trace via lax.scan).

    Packed-sequence (varlen) training: ``segment_ids [B, S]`` confines
    attention within each packed sequence (routed to the flash kernel's
    segment masking on TPU); ``position_ids [B, S]`` restarts RoPE positions
    per sequence (defaults to 0..S-1 shared across rows).

    MoE configs with ``return_aux=True`` return ``(logits, aux_loss)``
    (mean load-balancing loss over the layers).
    """
    from ..kernels.rope import rope_cos_sin
    B, S = input_ids.shape
    x = jnp.take(params["embed"], input_ids, axis=0).astype(cfg.dtype)
    if position_ids is None:
        cos, sin = rope_cos_sin(S, cfg.head_dim, cfg.rope_theta)
    else:
        pos = jnp.asarray(position_ids)
        if pos.ndim == 1:
            cos, sin = rope_cos_sin(S, cfg.head_dim, cfg.rope_theta,
                                    position_ids=pos)
        else:  # per-row positions -> [B, S, D] tables (jnp rope path)
            import functools as _ft
            mk = jax.vmap(_ft.partial(rope_cos_sin, S, cfg.head_dim,
                                      cfg.rope_theta))
            cos, sin = mk(position_ids=pos)

    layer = partial(decoder_layer, cos=cos, sin=sin, cfg=cfg,
                    segment_ids=segment_ids)
    if cfg.remat:
        layer = jax.checkpoint(layer, policy=_remat_policy(cfg.remat_policy))

    if cfg.moe_num_experts:
        def scan_body(h, lp):
            h, aux = layer(lp, h)
            return h, aux
    else:
        def scan_body(h, lp):
            return layer(lp, h), None

    x, auxes = lax.scan(scan_body, x, params["layers"])
    x = _rms_norm(x, params["ln_f"], cfg.rms_norm_eps, cfg.use_fused_norm)
    if return_hidden:   # chunked-CE path computes the head itself
        return x
    if cfg.tie_word_embeddings:
        logits = x @ params["embed"].T.astype(cfg.dtype)
    else:
        logits = _mm(x, params, "lm_head", cfg.dtype)
    if return_aux:  # dense configs report aux 0.0 — callers get a 2-tuple
        aux = jnp.mean(auxes) if cfg.moe_num_experts else jnp.float32(0.0)
        return logits, aux
    return logits


def loss_fn(params: Dict, input_ids, labels, cfg: LlamaConfig,
            segment_ids=None, position_ids=None):
    """Mean next-token cross-entropy (labels already shifted; -100 ignored).
    MoE configs add ``cfg.moe_aux_weight *`` the load-balancing loss.

    ``cfg.ce_chunks > 1`` computes the CE blockwise over token chunks (a
    lax.scan with per-chunk checkpoint): the full fp32 ``[T, V]`` logits and
    their cotangent never live in HBM at once — the memory headroom this
    frees is what lets ``remat_policy="save_flash"`` fit the bench config
    with fp32 Adam moments (see BASELINE.md roofline)."""
    if cfg.ce_chunks > 1 and not cfg.moe_num_experts:
        hidden = forward(params, input_ids, cfg, segment_ids, position_ids,
                         return_hidden=True)
        head = (params["embed"].T if cfg.tie_word_embeddings
                else params["lm_head"])
        B, S, E = hidden.shape
        T = B * S
        C = cfg.ce_chunks
        if T % C:
            raise ValueError(f"tokens {T} not divisible by ce_chunks {C}")
        h2 = hidden.reshape(C, T // C, E)
        lbl = labels.reshape(C, T // C)

        @jax.checkpoint
        def chunk(hc, lc):
            logits = (hc @ head.astype(cfg.dtype)).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, jnp.maximum(lc, 0)[..., None], axis=-1)[..., 0]
            m = lc >= 0
            return (jnp.where(m, lse - tgt, 0.0).sum(),
                    m.sum())

        def body(carry, xs):
            s, n = chunk(*xs)
            return (carry[0] + s, carry[1] + n), None

        (tot, cnt), _ = lax.scan(body, (jnp.float32(0.0), jnp.int32(0)),
                                 (h2, lbl))
        return tot / jnp.maximum(cnt, 1)
    logits, aux = forward(params, input_ids, cfg, segment_ids,
                          position_ids, return_aux=True)
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(
        logits, jnp.maximum(labels, 0)[..., None], axis=-1)[..., 0]
    mask = labels >= 0
    per_tok = jnp.where(mask, lse - tgt, 0.0)
    ce = per_tok.sum() / jnp.maximum(mask.sum(), 1)
    if cfg.moe_num_experts:
        ce = ce + cfg.moe_aux_weight * aux
    return ce


# ---------------------------------------------------------------------------
# functional train step (AdamW, fp32 master weights)
# ---------------------------------------------------------------------------

def _adamw_init(params, opt_dtype=jnp.float32):
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, opt_dtype), params)
    return {"m": zeros,
            "v": jax.tree_util.tree_map(jnp.copy, zeros),
            "step": jnp.zeros((), jnp.int32)}


def _adamw_apply(params, grads, opt_state, *, lr, beta1, beta2, eps,
                 weight_decay, opt_dtype, skip=None):
    """One AdamW update with fp32 moment arithmetic (multi_precision path).

    ``skip``: optional scalar bool (traced or eager) — when True the update
    is an exact state-preserving no-op, gated INSIDE the update math
    instead of by an output-side ``jnp.where(bad, old, new)`` over every
    buffer: the grads are masked to 0 through one fused elementwise select
    (``0 * NaN`` would stay NaN, a select doesn't) and the decay / step-size
    scalars collapse to identity (``beta -> 1``, ``lr -> 0``), so m/v/params
    pass through bit-exact and no second copy of the state is ever
    materialized. That keeps the sentinel's skip-step cost at a handful of
    scalar selects — the ``health_sentinel_overhead_pct`` bound rests on it.
    """
    if skip is None:
        step = opt_state["step"] + 1
        t = step.astype(jnp.float32)
    else:
        step = opt_state["step"] + (~skip).astype(jnp.int32)
        # a skipped FIRST step leaves t=0 -> bc1=0 -> u=0/0=NaN, and even
        # lr_eff=0 can't mask it (0*NaN=NaN); clamp — good steps have t>=1
        t = jnp.maximum(step.astype(jnp.float32), 1.0)
        b1_eff = jnp.where(skip, 1.0, beta1)
        b2_eff = jnp.where(skip, 1.0, beta2)
        c1_eff = jnp.where(skip, 0.0, 1 - beta1)
        c2_eff = jnp.where(skip, 0.0, 1 - beta2)
        lr_eff = jnp.where(skip, 0.0, lr)
    bc1 = 1.0 - beta1 ** t
    bc2 = 1.0 - beta2 ** t

    def upd(p, g, m, v):
        g = g.astype(jnp.float32)
        if skip is None:
            m = beta1 * m.astype(jnp.float32) + (1 - beta1) * g
            v = beta2 * v.astype(jnp.float32) + (1 - beta2) * (g * g)
        else:
            g = jnp.where(skip, 0.0, g)
            m = b1_eff * m.astype(jnp.float32) + c1_eff * g
            v = b2_eff * v.astype(jnp.float32) + c2_eff * (g * g)
        u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        pf = p.astype(jnp.float32)
        if weight_decay:
            u = u + weight_decay * pf
        return ((pf - (lr if skip is None else lr_eff) * u).astype(p.dtype),
                m.astype(opt_dtype), v.astype(opt_dtype))

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(opt_state["m"])
    flat_v = treedef.flatten_up_to(opt_state["v"])
    new = [upd(p, g, m, v) for p, g, m, v
           in zip(flat_p, flat_g, flat_m, flat_v)]
    params = jax.tree_util.tree_unflatten(treedef, [n[0] for n in new])
    m = jax.tree_util.tree_unflatten(treedef, [n[1] for n in new])
    v = jax.tree_util.tree_unflatten(treedef, [n[2] for n in new])
    return params, {"m": m, "v": v, "step": step}


def make_train_step(cfg: LlamaConfig, lr: float = 3e-4, beta1=0.9, beta2=0.95,
                    eps=1e-8, weight_decay=0.0, opt_dtype=jnp.float32,
                    grad_dtype=None, sentinel=False, spike_factor=None,
                    spike_warmup=None):
    """Returns ``(init_opt_state, train_step)`` pure functions.

    ``train_step(params, opt_state, input_ids, labels) ->
    (params, opt_state, loss)``. AdamW with the moment arithmetic in fp32
    (the reference's multi_precision optimizer path); ``opt_dtype`` sets the
    m/v STORAGE dtype (bf16 halves optimizer HBM for memory-bound configs —
    a documented quality trade, not the default).

    ``grad_dtype=bf16`` stores the grad TREE bf16: the weight grads are
    already produced by bf16-activation backward matmuls and only cast up
    at the boundary, so this adds a single extra rounding while XLA fuses
    the downcast into the producers — the fp32 grad tree (2.95GB at the
    bench config) never materializes. Moment arithmetic stays fp32.

    ``sentinel=True`` returns the health-guarded step instead:
    ``(params, opt_state, sent, input_ids, labels) ->
    (params, opt_state, sent, health)`` with ``sent`` from
    ``health.sentinel_init()`` and ``health`` the packed
    ``[loss, bad, ema]`` vector (``health.unpack_health``). Unlike the
    generic black-box ``health.guard_step`` wrapper — which must
    ``jnp.where``-select every output buffer against its old value — the
    bad-step gate here rides INSIDE ``_adamw_apply(skip=bad)``, so a good
    step is bit-identical to the unguarded step and the sentinel adds only
    the verdict reduction plus scalar selects.
    """

    def init_opt_state(params):
        return _adamw_init(params, opt_dtype)

    def _loss_and_grads(params, input_ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, input_ids, labels, cfg)
        if grad_dtype is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(grad_dtype), grads)
        return loss, grads

    def train_step(params, opt_state, input_ids, labels):
        loss, grads = _loss_and_grads(params, input_ids, labels)
        params, opt_state = _adamw_apply(
            params, grads, opt_state, lr=lr, beta1=beta1, beta2=beta2,
            eps=eps, weight_decay=weight_decay, opt_dtype=opt_dtype)
        return params, opt_state, loss

    def train_step_sentinel(params, opt_state, sent, input_ids, labels):
        from ..health.sentinel import pack_health, sentinel_check
        loss, grads = _loss_and_grads(params, input_ids, labels)
        bad, sent = sentinel_check(loss, sent, spike_factor=spike_factor,
                                   warmup=spike_warmup)
        params, opt_state = _adamw_apply(
            params, grads, opt_state, lr=lr, beta1=beta1, beta2=beta2,
            eps=eps, weight_decay=weight_decay, opt_dtype=opt_dtype,
            skip=bad)
        return params, opt_state, sent, pack_health(loss, bad, sent)

    return init_opt_state, (train_step_sentinel if sentinel else train_step)


# ---------------------------------------------------------------------------
# pipelined train step: ids -> loss in ONE compiled program over the pp axis
# ---------------------------------------------------------------------------

def to_pp_layout(params: Dict, num_stages: int, circular_repeats: int = 1):
    """Reshape the stacked ``[L, ...]`` layer params into pipeline layout
    ``[V, S, bpc, ...]`` (chunk ``c = v*S + s`` on device ``s``, lap ``v``;
    ``bpc`` blocks per chunk) so the chunk->device assignment is a plain
    shard of dim 1 over the ``pp`` mesh axis."""
    S, V = num_stages, circular_repeats
    out = dict(params)
    out["layers"] = jax.tree_util.tree_map(
        lambda p: p.reshape((V, S, p.shape[0] // (S * V)) + p.shape[1:]),
        params["layers"])
    return out


def from_pp_layout(params: Dict):
    """Inverse of :func:`to_pp_layout` (back to stacked ``[L, ...]``)."""
    out = dict(params)
    out["layers"] = jax.tree_util.tree_map(
        lambda p: p.reshape((-1,) + p.shape[3:]), params["layers"])
    return out


def pp_param_specs(cfg: LlamaConfig, pp_axis: str = "pp",
                   ep_axis: Optional[str] = None) -> Dict:
    """PartitionSpecs for pp-layout params: blocks sharded over the pp axis,
    embedding/LM-head VOCAB-sharded over the same axis (the heterogeneous
    first/last stages are not pipeline-isolated on TPU — they are
    tensor-parallel over the pp ranks, which turns the classic
    embedding-stage imbalance into useful parallel work; ref:
    pipeline_parallel.py first/last-stage special-casing).

    MoE configs: expert weights are ``[V, S, bpc, E, ...]`` — the expert dim
    additionally shards over ``ep_axis`` (defaults to ``cfg.ep_axis``), the
    pp x ep submesh composition (ref: the reference's large-MoE configs run
    pp+ep together)."""
    layer_keys = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                  "ln_attn", "ln_mlp")
    specs = {
        "embed": P(pp_axis, None),
        "layers": {k: P(None, pp_axis) for k in layer_keys},
        "ln_f": P(None),
    }
    if cfg.moe_num_experts:
        ep = ep_axis if ep_axis is not None else cfg.ep_axis
        for k in ("w_gate", "w_up", "w_down"):
            specs["layers"][k] = P(None, pp_axis, None, ep)
        specs["layers"]["moe_gate"] = P(None, pp_axis)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, pp_axis)
    return specs


def make_pp_train_step(cfg: LlamaConfig, mesh: Mesh, *, micro_batches: int,
                       pp_axis: str = "pp", dp_axis: Optional[str] = "dp",
                       circular_repeats: int = 1, lr: float = 3e-4,
                       beta1=0.9, beta2=0.95, eps=1e-8, weight_decay=0.0,
                       opt_dtype=jnp.float32):
    """Pipeline-parallel LLaMA training: the FULL step — vocab-parallel
    embedding, the circular ring schedule over decoder blocks, final norm,
    vocab-parallel LM head + cross-entropy, backward, AdamW — is one
    compiled XLA program; no per-micro-batch Python loop exists anywhere
    (SURVEY §3.4; ref: pipeline_parallel.py forward_backward_pipeline +
    ParallelCrossEntropy).

    Params must be in pp layout (:func:`to_pp_layout`); shard them with
    :func:`pp_param_specs` so block weights live only on their stage.

    Returns ``(init_opt_state, train_step)`` with
    ``train_step(params, opt_state, ids [B, T], labels) ->
    (params, opt_state, loss)``; ``B`` is split into ``micro_batches``.
    """
    from ..distributed.pipeline import ring_schedule
    from ..kernels.rope import rope_cos_sin

    S = int(mesh.shape[pp_axis])
    V = int(circular_repeats)
    M = int(micro_batches)
    L, Vo = cfg.num_hidden_layers, cfg.vocab_size
    if L % (S * V):
        raise ValueError(f"num_hidden_layers {L} not divisible by "
                         f"stages*circular_repeats = {S}*{V}")
    if Vo % S:
        raise ValueError(f"vocab_size {Vo} not divisible by pp degree {S}")
    moe = bool(cfg.moe_num_experts)
    # pp x ep composition: the pp ring runs MANUAL (shard_map over pp/dp);
    # the expert dim stays an AUTO axis — GSPMD shards the GShard dispatch/
    # combine einsums over `ep` INSIDE the manual region (sharding
    # constraints on the expert leaves; measured fwd+bwd working jax 0.9)
    ep = cfg.ep_axis if (moe and cfg.ep_axis and
                         cfg.ep_axis in mesh.axis_names) else None
    dpn = dp_axis if (dp_axis and dp_axis in mesh.axis_names) else None
    tree = jax.tree_util

    def body(embed_l, layers_l, ln_f, head_l, ids, labels):
        # embed_l [Vo/S, E]; layers_l leaves [V, 1, bpc, ...];
        # ids/labels [M, mb, T] (mb = local micro-batch after dp sharding)
        s = lax.axis_index(pp_axis)
        Vs = embed_l.shape[0]
        off = s * Vs
        Tq = ids.shape[-1]

        # ---- vocab-parallel embedding over the pp axis ----
        idx = ids - off
        ok = (idx >= 0) & (idx < Vs)
        e = jnp.take(embed_l, jnp.clip(idx, 0, Vs - 1), axis=0)
        e = jnp.where(ok[..., None], e, 0)
        x = lax.psum(e, pp_axis).astype(cfg.dtype)     # [M, mb, T, E]

        cos, sin = rope_cos_sin(Tq, cfg.head_dim, cfg.rope_theta)

        def chunk_fn(cp, h):
            # cp leaves [bpc, ...]: apply the chunk's blocks sequentially
            def blk(hh, lp):
                if moe:
                    if ep is not None:  # expert dim: GSPMD auto axis
                        lp = dict(lp)
                        for kk in ("w_gate", "w_up", "w_down"):
                            lp[kk] = lax.with_sharding_constraint(
                                lp[kk], P(ep, None, None))
                    return decoder_layer(lp, hh, cos, sin, cfg)
                return decoder_layer(lp, hh, cos, sin, cfg), None
            h, auxes = lax.scan(blk, h, cp)
            if moe:  # chunk aux = sum over its bpc layers
                return h, jnp.sum(auxes)
            return h

        fn = jax.checkpoint(chunk_fn) if cfg.remat else chunk_fn
        mine = tree.tree_map(lambda p: p[:, 0], layers_l)
        res = ring_schedule(fn, mine, x, axis=pp_axis, num_stages=S,
                            circular_repeats=V, with_aux=moe)
        outs, aux_total = res if moe else (res, None)   # outs [M, mb, T, E]

        # ---- final norm + vocab-parallel LM head + cross-entropy ----
        h = _rms_norm(outs, ln_f, cfg.rms_norm_eps, cfg.use_fused_norm)
        hd = embed_l.T if cfg.tie_word_embeddings else head_l  # [E, Vo/S]
        z = (h @ hd.astype(cfg.dtype)).astype(jnp.float32)  # [M, mb, T, Vo/S]
        lmax = lax.pmax(lax.stop_gradient(z).max(axis=-1), pp_axis)
        lse = jnp.log(lax.psum(
            jnp.exp(z - lmax[..., None]).sum(axis=-1), pp_axis)) + lmax
        lidx = labels - off
        inshard = (lidx >= 0) & (lidx < Vs)
        tgt_l = jnp.take_along_axis(
            z, jnp.clip(lidx, 0, Vs - 1)[..., None], axis=-1)[..., 0]
        tgt = lax.psum(jnp.where(inshard, tgt_l, 0.0), pp_axis)
        mask = labels >= 0
        lsum = jnp.where(mask, lse - tgt, 0.0).sum()
        cnt = mask.sum()
        if dpn is not None:
            lsum = lax.psum(lsum, dpn)
            cnt = lax.psum(cnt, dpn)
        loss = lsum / jnp.maximum(cnt, 1)
        if moe:
            # serial-equivalent normalization: micro-batched serial loss is
            # mean over M of (ce_m + w * mean_l aux_{l,m}); aux_total sums
            # every (layer, micro-batch) application -> divide by L*M
            aux_mean = aux_total / (L * M)
            if dpn is not None:
                aux_mean = lax.pmean(aux_mean, dpn)
            loss = loss + cfg.moe_aux_weight * aux_mean
        return loss

    def pp_loss(params, ids_m, labels_m):
        layers = params["layers"]
        in_layer_spec = tree.tree_map(lambda p: P(None, pp_axis), layers)
        bspec = P(None, dpn, None) if dpn else P(None, None, None)
        head = None if cfg.tie_word_embeddings else params["lm_head"]
        extra = {}
        if ep is not None:
            # manual axes = the ring + dp; `ep` stays auto so GSPMD shards
            # the expert einsums inside the manual region
            extra["axis_names"] = frozenset(
                {pp_axis} | ({dpn} if dpn else set()))
        shmap = shard_map(
            body, mesh=mesh,
            in_specs=(P(pp_axis, None), in_layer_spec, P(None),
                      (P(None, pp_axis) if head is not None else P()),
                      bspec, bspec),
            out_specs=P(), check_vma=False, **extra)
        if head is None:
            head = jnp.zeros((), cfg.param_dtype)  # placeholder (unused)
        return shmap(params["embed"], layers, params["ln_f"], head,
                     ids_m, labels_m)

    def init_opt_state(params):
        return _adamw_init(params, opt_dtype)

    def train_step(params, opt_state, input_ids, labels):
        B = input_ids.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible by micro_batches {M}")
        ids_m = input_ids.reshape(M, B // M, -1)
        lbl_m = labels.reshape(M, B // M, -1)
        loss, grads = jax.value_and_grad(pp_loss)(params, ids_m, lbl_m)
        params, opt_state = _adamw_apply(
            params, grads, opt_state, lr=lr, beta1=beta1, beta2=beta2,
            eps=eps, weight_decay=weight_decay, opt_dtype=opt_dtype)
        return params, opt_state, loss

    return init_opt_state, train_step


# ---------------------------------------------------------------------------
# eager nn.Layer wrapper (imperative API parity)
# ---------------------------------------------------------------------------

class LlamaForCausalLM:
    """Eager wrapper exposing the functional model as an ``nn.Layer``.

    Implemented lazily (class body built on first instantiation) to keep the
    functional core import-light for bench/driver entry points.
    """

    def __new__(cls, config: LlamaConfig, key: Optional[jax.Array] = None):
        from ..core.tensor import Parameter, Tensor
        from ..core.dispatch import forward_op
        from ..nn.layer import Layer

        class _Llama(Layer):
            def __init__(self, cfg, key):
                super().__init__()
                self.config = cfg
                key = key if key is not None else jax.random.PRNGKey(0)
                raw = init_params(cfg, key)
                flat, self._treedef = jax.tree_util.tree_flatten(raw)
                self._flat_params = []
                for i, leaf in enumerate(flat):
                    p = Parameter(leaf)
                    self.add_parameter(f"p{i}", p)
                    self._flat_params.append(p)

            def params_pytree(self):
                return jax.tree_util.tree_unflatten(
                    self._treedef, [p._value for p in self._flat_params])

            def forward(self, input_ids, labels=None):
                cfg = self.config
                n = len(self._flat_params)

                if labels is None:
                    def f(ids, *leaves):
                        params = jax.tree_util.tree_unflatten(
                            self._treedef, list(leaves))
                        return forward(params, ids, cfg)
                    return forward_op("llama_forward", f,
                                      [input_ids, *self._flat_params])

                def f(ids, lbl, *leaves):
                    params = jax.tree_util.tree_unflatten(
                        self._treedef, list(leaves))
                    return loss_fn(params, ids, lbl, cfg)
                return forward_op("llama_loss", f,
                                  [input_ids, labels, *self._flat_params])

            def generate(self, input_ids, *, max_new_tokens=None,
                         prompt_lens=None, temperature=None,
                         top_k="unset", top_p="unset",
                         eos_token_id="unset",
                         pad_token_id=None, seed=None,
                         generation_config=None):
                """KV-cache autoregressive decoding (greedy when
                ``temperature == 0``, else top-k/top-p sampling); prefill +
                the whole decode loop compile to ONE device program — see
                :mod:`paddle_tpu.models.generation`.

                Sampling knobs resolve through the ONE shared
                :class:`~paddle_tpu.models.generation.GenerationConfig`
                (also the ``inference.GenerationPredictor`` struct):
                ``generation_config`` supplies defaults, explicit keyword
                arguments override its fields — including an explicit
                ``eos_token_id=None``/``top_k=None``/``top_p=None`` to
                DISABLE a knob the base config sets (their not-given
                spelling is the ``"unset"`` sentinel)."""
                from .generation import GenerationConfig
                from .generation import generate as _gen
                g = GenerationConfig.resolve(
                    generation_config, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    eos_token_id=eos_token_id, pad_token_id=pad_token_id,
                    seed=seed)
                ids = getattr(input_ids, "_value", input_ids)
                out = _gen(self.params_pytree(), ids, self.config,
                           max_new_tokens=g.max_new_tokens,
                           prompt_lens=getattr(prompt_lens, "_value",
                                               prompt_lens),
                           temperature=g.temperature, top_k=g.top_k,
                           top_p=g.top_p, eos_token_id=g.eos_token_id,
                           pad_token_id=g.pad_token_id,
                           key=jax.random.PRNGKey(g.seed))
                return Tensor(out)

        _Llama.__name__ = "LlamaForCausalLM"
        return _Llama(config, key)
