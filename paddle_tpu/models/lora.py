"""Multi-adapter LoRA serving: registry + device-resident paged adapter pool.

The S-LoRA / Punica shape adapted to this repo's compile-once paged
engine (ISSUE 19): N per-customer LoRA fine-tunes share ONE base model
and ONE set of compiled programs, so a fine-tune costs adapter weights
(two rank-r factors per attention projection per layer), not a replica.

* **One stacked pool, one program.** Every registered adapter's A/B
  factors live at a FIXED rank ``r`` in a stacked device pool
  ``[L, slots, ...]`` (:class:`AdapterPool`). Each serving dispatch
  carries a per-row ``adapter slot id`` array — a DEVICE OPERAND of the
  one compiled program, exactly like the PR 11 sampling-knob arrays — and
  the layer body applies the gathered batched adapter matmul
  ``y += (x @ A[ids]) @ B[ids]`` fused into the q/k/v/o projections
  (:func:`lora_delta`). Adapter churn (register / evict / reload) only
  rewrites pool rows and the id operand: the trace-counter tests prove
  zero recompiles across any adapter mix.
* **Slot 0 is the zeroed BASE adapter.** Requests without an adapter
  gather all-zero factors, and the delta they add is an exact ``+0.0`` —
  floating-point addition of a zero product can only normalize ``-0.0``
  to ``+0.0``, which no argmax or categorical draw can observe, so base
  traffic through a LoRA-enabled engine emits token streams BIT-IDENTICAL
  to the LoRA-less build (pinned across fp32/int8 x kernel/gather x
  greedy/seeded x TP degrees by tests/test_lora.py).
* **Host LRU tier.** Cold adapters live in a host-side registry
  (checksummed numpy copies, the PR 16 offload-tier discipline: crc32 at
  registration, verified again at every H2D load so a corrupted host
  copy becomes a structured error, never silently-wrong weights). The
  pool LRU-evicts the coldest UNPINNED resident adapter to make room;
  running requests pin theirs, so an in-flight stream's weights can
  never be swapped out from under it. Evict + reload round-trips are
  bit-exact: the same bytes reload into whatever slot is free.
* **Tensor parallelism.** Under the serving TP mesh the ``qB``/``kB``/
  ``vB`` pool leaves shard their output-feature axis exactly like the
  column-sharded ``wq``/``wk``/``wv`` they feed (each shard's delta is
  its local head slice); ``oA``/``oB`` replicate (the wo projection runs
  replicated on the all-gathered merged heads). :func:`lora_pool_specs`
  is the one spec map both the pool's ``device_put`` and the engine's
  ``shard_map`` in_specs read.

The merged-dense oracle (:func:`merge_lora`) folds ``W + A @ B`` into a
plain parameter tree so the dense ``generate()`` tier reproduces each
adapter's greedy token stream — the engine's factored spelling
``x @ W + (x @ A) @ B`` and the merged ``x @ (W + A B)`` differ in fp
rounding, but not by enough to move any greedy argmax in the pinned
configs, so the oracle check is token-exact.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig

__all__ = ["AdapterPool", "lora_param_shapes", "lora_init_params",
           "lora_delta", "lora_pool_specs", "merge_lora"]


# the four attention projections LoRA targets: (weight leaf, A leaf, B leaf)
_TARGETS = (("wq", "qA", "qB"), ("wk", "kA", "kB"),
            ("wv", "vA", "vB"), ("wo", "oA", "oB"))


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def lora_param_shapes(cfg: LlamaConfig, rank: int) -> Dict[str, tuple]:
    """Per-adapter factor shapes (leading L = stacked layers): ``A`` maps
    the projection input to rank ``r``, ``B`` maps rank ``r`` to the
    projection output — matching the stacked llama weights ``wq [L, E,
    H*D]`` / ``wk``/``wv [L, E, Hk*D]`` / ``wo [L, H*D, E]``."""
    L, E = cfg.num_hidden_layers, cfg.hidden_size
    H, Hk, D = cfg.num_attention_heads, cfg.kv_heads, cfg.head_dim
    r = int(rank)
    return {"qA": (L, E, r), "qB": (L, r, H * D),
            "kA": (L, E, r), "kB": (L, r, Hk * D),
            "vA": (L, E, r), "vB": (L, r, Hk * D),
            "oA": (L, H * D, r), "oB": (L, r, E)}


def lora_init_params(cfg: LlamaConfig, rank: int, seed: int = 0,
                     scale: float = 0.05) -> Dict[str, np.ndarray]:
    """A random host-side adapter (both factors nonzero — a zero ``B``
    would be indistinguishable from the base adapter and prove nothing
    in any parity test). fp32, numpy: adapters register from the host."""
    rng = np.random.default_rng(seed)
    return {n: (rng.standard_normal(s) * scale).astype(np.float32)
            for n, s in lora_param_shapes(cfg, rank).items()}


def lora_delta(x, la, lb, ids, dt):
    """The gathered batched adapter matmul for one layer's projection:
    ``(x @ A[ids]) @ B[ids]`` with ``x [B, T, in]``, per-layer pool
    slices ``la [slots, in, r]`` / ``lb [slots, r, out]`` and ``ids [B]``
    int32 adapter slots (a device operand — churn never retraces).
    Returns the ``[B, T, out]`` delta in compute dtype ``dt``; slot 0's
    zeroed factors make it an exact ``+0.0`` for base rows."""
    a = jnp.take(la, ids, axis=0).astype(dt)         # [B, in, r]
    b = jnp.take(lb, ids, axis=0).astype(dt)         # [B, r, out]
    t = jnp.einsum("bti,bir->btr", x.astype(dt), a)
    return jnp.einsum("btr,bro->bto", t, b)


def lora_pool_specs(layers: Dict, mesh, axis: str = "tp") -> Dict:
    """PartitionSpecs for the stacked pool leaves under serving TP:
    ``qB``/``kB``/``vB`` shard their output-feature axis (dim -1) exactly
    like the column-sharded projections they add into; everything else
    replicates (``oA``/``oB`` feed the replicated wo on merged heads).
    Indivisible shapes raise the structured ``shard_dim_spec`` error
    naming the leaf."""
    from jax.sharding import PartitionSpec

    from ..distributed.sharding import shard_dim_spec
    out = {}
    for name, leaf in layers.items():
        if name in ("qB", "kB", "vB"):
            out[name] = shard_dim_spec(leaf.shape, mesh, axis, dim=-1,
                                       name=f"lora_pool.{name}")
        else:
            out[name] = PartitionSpec()
    return out


def merge_lora(params: Dict, lora_params: Dict[str, np.ndarray]) -> Dict:
    """The DENSE ORACLE: fold one adapter into a copy of the stacked
    llama params (``W += A @ B`` per projection per layer) so the plain
    dense ``generate()`` path reproduces the adapter's greedy stream.
    fp params only — the int8 engine path quantizes the BASE weights and
    adds the fp delta outside the quantized matmul, which a merged int8
    weight could not represent."""
    layers = dict(params["layers"])
    for wname, aname, bname in _TARGETS:
        a = jnp.asarray(lora_params[aname], jnp.float32)
        b = jnp.asarray(lora_params[bname], jnp.float32)
        w = layers[wname]
        layers[wname] = (w.astype(jnp.float32)
                        + jnp.einsum("lir,lro->lio", a, b)).astype(w.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


class AdapterPool:
    """Device-resident paged adapter pool + host LRU registry.

    ``slots`` device rows hold loaded adapters (slot 0 is the reserved
    zeroed base adapter on top of that); up to ``capacity`` adapters may
    be registered host-side in total. ``acquire`` pins an adapter
    resident (loading it over the LRU unpinned victim if cold) and
    ``release`` unpins it; a fully pinned pool makes ``acquire`` return
    None — the scheduler's admission gate SKIPS that request (no
    head-of-line blocking) and retries at the next step.
    """

    def __init__(self, cfg: LlamaConfig, rank: int, slots: int,
                 capacity: int, mesh=None, tp_axis: str = "tp"):
        rank, slots, capacity = int(rank), int(slots), int(capacity)
        if rank < 1:
            raise ValueError(
                f"FLAGS_serving_lora_rank must be >= 1, got {rank}")
        if slots < 1:
            raise ValueError(
                f"AdapterPool needs FLAGS_serving_lora_slots >= 1 device "
                f"slots, got {slots} (0 disables multi-adapter serving "
                f"at the engine, not here)")
        if capacity < slots:
            raise ValueError(
                f"FLAGS_serving_lora_pool ({capacity}) must be >= "
                f"FLAGS_serving_lora_slots ({slots}): the host registry "
                f"backs every resident adapter")
        self.cfg, self.rank = cfg, rank
        self.num_slots = slots          # loadable slots (1..slots)
        self.capacity = capacity
        self._shapes = lora_param_shapes(cfg, rank)
        # stacked [L, slots+1, ...] pool; row 0 = the zeroed base adapter
        self.layers = {
            n: jnp.zeros((s[0], slots + 1) + s[1:], jnp.float32)
            for n, s in self._shapes.items()}
        if mesh is not None:
            from jax.sharding import NamedSharding
            specs = lora_pool_specs(self.layers, mesh, tp_axis)
            import jax
            self.layers = {n: jax.device_put(a, NamedSharding(mesh,
                                                              specs[n]))
                           for n, a in self.layers.items()}
        # host registry: name -> {"data": {leaf: np}, "crc": {leaf: int}}
        self._host: "OrderedDict[str, Dict]" = OrderedDict()
        self._resident: Dict[str, int] = {}       # name -> slot (1-based)
        self._slot_name: List[Optional[str]] = [None] * (slots + 1)
        self._pins: Dict[str, int] = {}           # name -> pin count
        self._lru: "OrderedDict[str, None]" = OrderedDict()  # resident LRU
        self.loads = 0                 # H2D adapter uploads (cold acquires)
        self.evictions = 0

    # ---- registry ---------------------------------------------------------

    def register(self, name: str, params: Dict[str, np.ndarray]) -> None:
        """Accept one adapter into the host registry (checksummed copy).
        Shape/rank mismatches and a full registry are structured errors —
        wrong factors must fail at registration, not deep inside a
        gathered einsum."""
        if not isinstance(name, str) or not name:
            raise ValueError(f"adapter name must be a non-empty string, "
                             f"got {name!r}")
        if name not in self._host and len(self._host) >= self.capacity:
            raise ValueError(
                f"adapter registry full ({self.capacity} adapters): "
                f"cannot register {name!r}; raise FLAGS_serving_lora_pool "
                f"or deregister a cold adapter")
        missing = set(self._shapes) - set(params)
        if missing:
            raise ValueError(f"adapter {name!r} is missing factor leaves "
                             f"{sorted(missing)}; expected "
                             f"{sorted(self._shapes)}")
        data = {}
        for leaf, shape in self._shapes.items():
            arr = np.asarray(params[leaf], np.float32)
            if arr.shape != shape:
                raise ValueError(
                    f"adapter {name!r} leaf {leaf!r} has shape "
                    f"{arr.shape}, expected {shape} (rank "
                    f"FLAGS_serving_lora_rank={self.rank} over "
                    f"{self._shapes['qA'][0]} layers)")
            # a real copy, not a view: the registry must own its bytes,
            # or a caller mutating (or freeing) the factors after
            # registration silently invalidates the checksummed copy
            data[leaf] = np.array(arr, np.float32, order="C", copy=True)
        if name in self._resident:
            # re-registration of a RESIDENT adapter replaces its bytes:
            # drop residency so the next acquire uploads the new factors
            # (pinned adapters cannot be silently swapped mid-stream)
            if self._pins.get(name, 0):
                raise ValueError(
                    f"adapter {name!r} is pinned by running requests; "
                    f"cannot replace its weights mid-stream")
            self._evict(name)
        self._host[name] = {"data": data,
                            "crc": {n: _crc(a) for n, a in data.items()}}

    def is_registered(self, name: str) -> bool:
        return name in self._host

    def registered(self) -> List[str]:
        return list(self._host)

    # ---- residency --------------------------------------------------------

    def acquire(self, name: str) -> Optional[int]:
        """Pin ``name`` resident and return its slot; None when every
        slot is pinned by other adapters (the caller skips and retries).
        Cold acquires verify the host copy's checksums and upload it
        into the freed slot (one ``adapter_loads`` tick)."""
        if name not in self._host:
            raise KeyError(f"adapter {name!r} is not registered")
        slot = self._resident.get(name)
        if slot is None:
            slot = self._free_slot()
            if slot is None:
                return None
            entry = self._host[name]
            for leaf, arr in entry["data"].items():
                if _crc(arr) != entry["crc"][leaf]:
                    raise RuntimeError(
                        f"adapter {name!r} leaf {leaf!r} failed its "
                        f"load-time checksum: host copy corrupted; "
                        f"refusing to serve wrong weights")
            for leaf, arr in entry["data"].items():
                self.layers[leaf] = \
                    self.layers[leaf].at[:, slot].set(jnp.asarray(arr))
            self._resident[name] = slot
            self._slot_name[slot] = name
            self.loads += 1
        self._pins[name] = self._pins.get(name, 0) + 1
        self._lru.pop(name, None)
        self._lru[name] = None                      # most recently used
        return slot

    def release(self, name: str) -> None:
        """Drop one pin; the adapter STAYS resident (warm) until the LRU
        needs its slot."""
        n = self._pins.get(name, 0)
        if n <= 1:
            self._pins.pop(name, None)
        else:
            self._pins[name] = n - 1

    def _free_slot(self) -> Optional[int]:
        for s in range(1, self.num_slots + 1):
            if self._slot_name[s] is None:
                return s
        for victim in self._lru:                    # oldest first
            if not self._pins.get(victim, 0):
                slot = self._resident[victim]
                self._evict(victim)
                self.evictions += 1
                return slot
        return None

    def _evict(self, name: str) -> None:
        slot = self._resident.pop(name)
        self._slot_name[slot] = None
        self._lru.pop(name, None)
        self._pins.pop(name, None)

    def resident(self) -> Dict[str, int]:
        return dict(self._resident)

    def evicted(self) -> List[str]:
        return [n for n in self._host if n not in self._resident]

    def pinned(self) -> Dict[str, int]:
        return dict(self._pins)

    def slot_of(self, name: str) -> Optional[int]:
        return self._resident.get(name)

    # ---- observability + chaos --------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {"adapters_registered": len(self._host),
                "adapters_resident": len(self._resident),
                "adapter_loads": self.loads,
                "adapter_evictions": self.evictions,
                "adapter_pins": sum(self._pins.values())}

    def snapshot(self) -> Dict:
        out = self.stats()
        out["rank"] = self.rank
        out["slots"] = self.num_slots
        out["resident"] = sorted(self._resident)
        return out

    def corrupt_one(self) -> Optional[str]:
        """Chaos hook (the offload tier's discipline): flip one byte of
        one COLD adapter's host copy. The next acquire of that adapter
        fails its load-time checksum with a structured error instead of
        serving wrong weights. Returns the adapter corrupted, or None
        when every registered adapter is resident."""
        for name in self._host:
            if name in self._resident:
                continue
            leaf = next(iter(self._shapes))
            buf = self._host[name]["data"][leaf]
            buf.view(np.uint8).reshape(-1)[0] ^= 0xFF
            return name
        return None
