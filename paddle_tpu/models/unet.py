"""Diffusion UNet (SDXL-style) — the ppdiffusers capability target.

Capability target: the reference ecosystem's SDXL UNet (ppdiffusers
``models/unet_2d_condition.py``: timestep-embedded ResBlocks,
cross-attention transformer blocks at the lower resolutions, down/up paths
with skip connections; BASELINE.json configs[4] names "SDXL UNet (Pallas
attention)"). This is the architecture at configurable width/depth — the
bench row drives the heavy attention shapes through the Pallas flash
kernel; tests train a tiny instance end to end on the epsilon-prediction
objective.

TPU notes: NCHW throughout (the repo's conv convention); attention flattens
spatial to sequence and runs scaled-dot-product attention — the self-attn
at 64x64 latents (S=4096) is exactly the `bench.py --sdxl` kernel shape;
GroupNorm/SiLU ride XLA fusion.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import forward_op
from ..core.tensor import Tensor
from ..nn import (Conv2D, GroupNorm, Identity, LayerNorm, Linear, SiLU,
                  Sequential)
from ..nn.layer import Layer

__all__ = ["UNet2DConditionModel", "sdxl_unet_mini", "timestep_embedding"]


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding [B] -> [B, dim] (DDPM convention)."""
    def impl(tv):
        half = dim // 2
        freqs = jnp.exp(-math.log(max_period) *
                        jnp.arange(half, dtype=jnp.float32) / half)
        args = tv.astype(jnp.float32)[:, None] * freqs[None]
        return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    # Tensors pass through unchanged (a to-host round trip would break
    # under a to_static trace); only raw arrays/lists get wrapped.
    return forward_op("timestep_embedding", impl,
                      [t if isinstance(t, Tensor) else
                       __import__("paddle_tpu").to_tensor(np.asarray(t))])


def _groups(c: int, cap: int = 8) -> int:
    """Largest divisor of ``c`` not exceeding ``cap`` (GroupNorm needs
    groups | channels)."""
    for g in range(min(cap, c), 0, -1):
        if c % g == 0:
            return g
    return 1


class ResBlock(Layer):
    """GroupNorm-SiLU-Conv x2 with the timestep embedding added between
    (ref: ppdiffusers ResnetBlock2D)."""

    def __init__(self, cin, cout, temb_dim, groups=8):
        super().__init__()
        self.norm1 = GroupNorm(_groups(cin, groups), cin)
        self.conv1 = Conv2D(cin, cout, 3, padding=1)
        self.temb_proj = Linear(temb_dim, cout)
        self.norm2 = GroupNorm(_groups(cout, groups), cout)
        self.conv2 = Conv2D(cout, cout, 3, padding=1)
        self.act = SiLU()
        self.skip = Conv2D(cin, cout, 1) if cin != cout else Identity()

    def forward(self, x, temb):
        h = self.conv1(self.act(self.norm1(x)))
        from ..ops.manipulation import reshape
        e = self.temb_proj(self.act(temb))
        B, C = e.shape
        h = h + reshape(e, [B, C, 1, 1])
        h = self.conv2(self.act(self.norm2(h)))
        return h + self.skip(x)


class CrossAttnBlock(Layer):
    """LayerNorm'd self-attention + cross-attention + GEGLU-ish FF over the
    flattened spatial sequence (ref: ppdiffusers Transformer2DModel basic
    block, single layer)."""

    def __init__(self, channels, ctx_dim, heads=4):
        super().__init__()
        if channels % heads:
            raise ValueError(f"channels {channels} % heads {heads}")
        self.heads = heads
        self.norm_in = GroupNorm(_groups(channels), channels)
        self.ln1 = LayerNorm(channels)
        self.to_q1 = Linear(channels, channels)
        self.to_k1 = Linear(channels, channels)
        self.to_v1 = Linear(channels, channels)
        self.out1 = Linear(channels, channels)
        self.ln2 = LayerNorm(channels)
        self.to_q2 = Linear(channels, channels)
        self.to_k2 = Linear(ctx_dim, channels)
        self.to_v2 = Linear(ctx_dim, channels)
        self.out2 = Linear(channels, channels)
        self.ln3 = LayerNorm(channels)
        self.ff = Sequential(Linear(channels, 4 * channels), SiLU(),
                             Linear(4 * channels, channels))

    def _attn(self, q, k, v):
        """[B, S, C] x [B, T, C] -> [B, S, C] multi-head SDPA (the flash
        kernel path is used by nn.functional on TPU shapes; the jnp path is
        the oracle on CPU)."""
        from ..nn.functional import scaled_dot_product_attention
        from ..ops.manipulation import reshape
        B, S, C = q.shape
        T = k.shape[1]
        H = self.heads
        D = C // H
        qh = reshape(q, [B, S, H, D])
        kh = reshape(k, [B, T, H, D])
        vh = reshape(v, [B, T, H, D])
        o = scaled_dot_product_attention(qh, kh, vh)
        return reshape(o, [B, S, C])

    def forward(self, x, context):
        from ..ops.manipulation import reshape, transpose
        B, C, H, W = x.shape
        h = self.norm_in(x)
        seq = transpose(reshape(h, [B, C, H * W]), [0, 2, 1])  # [B, S, C]
        a = self.ln1(seq)
        seq = seq + self.out1(self._attn(self.to_q1(a), self.to_k1(a),
                                         self.to_v1(a)))
        a = self.ln2(seq)
        seq = seq + self.out2(self._attn(self.to_q2(a),
                                         self.to_k2(context),
                                         self.to_v2(context)))
        seq = seq + self.ff(self.ln3(seq))
        out = reshape(transpose(seq, [0, 2, 1]), [B, C, H, W])
        return x + out


class Downsample(Layer):
    def __init__(self, c):
        super().__init__()
        self.conv = Conv2D(c, c, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample(Layer):
    def __init__(self, c):
        super().__init__()
        self.conv = Conv2D(c, c, 3, padding=1)

    def forward(self, x):
        B, C, H, W = x.shape

        def up(v):
            return jax.image.resize(v, (v.shape[0], v.shape[1],
                                        2 * H, 2 * W), method="nearest")
        return self.conv(forward_op("unet_upsample", up, [x]))


class UNet2DConditionModel(Layer):
    """Conditional UNet: eps = f(x_t, t, context).

    ``block_out_channels`` sets the per-level widths; cross-attention runs
    at every level except the first (the SDXL layout: attention at the
    lower spatial resolutions).
    """

    def __init__(self, in_channels: int = 4,
                 block_out_channels: Sequence[int] = (32, 64, 96),
                 ctx_dim: int = 64, heads: int = 4,
                 layers_per_block: int = 1):
        super().__init__()
        chans = list(block_out_channels)
        temb = 4 * chans[0]
        self._temb_base = chans[0]
        self.time_mlp = Sequential(Linear(chans[0], temb), SiLU(),
                                   Linear(temb, temb))
        self.conv_in = Conv2D(in_channels, chans[0], 3, padding=1)

        self.down_res: List = []
        self.down_attn: List = []
        self.downs: List = []
        c = chans[0]
        for li, co in enumerate(chans):
            for bi in range(layers_per_block):
                r = ResBlock(c, co, temb)
                self.add_sublayer(f"dres{li}_{bi}", r)
                self.down_res.append((li, r))
                a = CrossAttnBlock(co, ctx_dim, heads) if li > 0 else None
                if a is not None:
                    self.add_sublayer(f"dattn{li}_{bi}", a)
                self.down_attn.append(a)
                c = co
            if li < len(chans) - 1:
                d = Downsample(co)
                self.add_sublayer(f"down{li}", d)
                self.downs.append(d)

        self.mid1 = ResBlock(c, c, temb)
        self.mid_attn = CrossAttnBlock(c, ctx_dim, heads)
        self.mid2 = ResBlock(c, c, temb)

        self.up_res: List = []
        self.up_attn: List = []
        self.ups: List = []
        for li, co in reversed(list(enumerate(chans))):
            for bi in range(layers_per_block):
                r = ResBlock(c + co, co, temb)   # skip concat
                self.add_sublayer(f"ures{li}_{bi}", r)
                self.up_res.append((li, r))
                a = CrossAttnBlock(co, ctx_dim, heads) if li > 0 else None
                if a is not None:
                    self.add_sublayer(f"uattn{li}_{bi}", a)
                self.up_attn.append(a)
                c = co
            if li > 0:
                u = Upsample(co)
                self.add_sublayer(f"up{li}", u)
                self.ups.append(u)

        self.norm_out = GroupNorm(_groups(c), c)
        self.act = SiLU()
        self.conv_out = Conv2D(c, in_channels, 3, padding=1)

    def forward(self, x, t, context):
        from ..ops.extras import hstack  # noqa: F401 (namespace warm)
        from ..ops.manipulation import concat
        temb = self.time_mlp(timestep_embedding(t, self._temb_base))
        h = self.conv_in(x)
        skips = []
        di = 0
        res_i = 0
        n_levels = (len(self.downs) + 1)
        per = len(self.down_res) // n_levels
        for li in range(n_levels):
            for _ in range(per):
                _, r = self.down_res[res_i]
                h = r(h, temb)
                a = self.down_attn[res_i]
                if a is not None:
                    h = a(h, context)
                skips.append(h)
                res_i += 1
            if li < n_levels - 1:
                h = self.downs[di](h)
                di += 1

        h = self.mid2(self.mid_attn(self.mid1(h, temb), context), temb)

        ui = 0
        res_i = 0
        for li in range(n_levels):
            for _ in range(per):
                _, r = self.up_res[res_i]
                h = r(concat([h, skips.pop()], axis=1), temb)
                a = self.up_attn[res_i]
                if a is not None:
                    h = a(h, context)
                res_i += 1
            if li < n_levels - 1:
                h = self.ups[ui](h)
                ui += 1

        return self.conv_out(self.act(self.norm_out(h)))


def sdxl_unet_mini(**kw) -> UNet2DConditionModel:
    """Test/bench-scale instance of the SDXL layout."""
    return UNet2DConditionModel(**kw)
