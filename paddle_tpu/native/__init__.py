"""Native (C++) runtime components, consumed via ctypes.

The reference implements its coordination store and DataLoader shared-memory
transport in C++ (``paddle/fluid/distributed/store/tcp_store.cc``, the
dataloader shm transport); these are their TPU-rebuild equivalents, compiled
from ``native/*.cc`` with g++ on first use (no pybind11 in this image — the
bindings are a plain C ABI + ctypes).

Public surface: :class:`TCPStore` (master-hosted rendezvous KV with
set/get/wait/add) and :class:`ShmRing` (single-producer single-consumer
shared-memory ring used by ``io.DataLoader`` when ``use_shared_memory``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = ["TCPStore", "ShmRing", "lib", "build_native"]

_REPO_NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpaddle_tpu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def build_native(force: bool = False) -> str:
    """Compile native/*.cc into one shared library (cached by source HASH —
    an mtime check can be fooled by a stale artifact newer than edited
    sources, e.g. after a checkout)."""
    import hashlib
    srcs = [os.path.join(_REPO_NATIVE, f)
            for f in sorted(os.listdir(_REPO_NATIVE)) if f.endswith(".cc")]
    if not srcs:
        raise RuntimeError(f"no native sources found in {_REPO_NATIVE}")
    os.makedirs(_BUILD_DIR, exist_ok=True)
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()
    stamp = os.path.join(_BUILD_DIR, "source.sha256")
    if not force and os.path.exists(_LIB_PATH) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == digest:
                return _LIB_PATH
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *srcs, "-o", _LIB_PATH, "-lrt"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n{' '.join(cmd)}\n{proc.stderr}")
    with open(stamp, "w") as f:
        f.write(digest)
    return _LIB_PATH


def lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            path = build_native()
            L = ctypes.CDLL(path)
            # tcp_store
            L.tcp_store_server_start.restype = ctypes.c_void_p
            L.tcp_store_server_start.argtypes = [ctypes.c_int]
            L.tcp_store_server_port.restype = ctypes.c_int
            L.tcp_store_server_port.argtypes = [ctypes.c_void_p]
            L.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
            L.tcp_store_client_connect.restype = ctypes.c_void_p
            L.tcp_store_client_connect.argtypes = [ctypes.c_char_p,
                                                   ctypes.c_int]
            L.tcp_store_set.restype = ctypes.c_int
            L.tcp_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_char_p, ctypes.c_uint64]
            L.tcp_store_get.restype = ctypes.c_int64
            L.tcp_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_void_p, ctypes.c_uint64]
            L.tcp_store_add.restype = ctypes.c_int64
            L.tcp_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int64]
            L.tcp_store_check.restype = ctypes.c_int
            L.tcp_store_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            L.tcp_store_delete.restype = ctypes.c_int
            L.tcp_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            L.tcp_store_client_close.argtypes = [ctypes.c_void_p]
            # shm_ring
            L.shm_ring_create.restype = ctypes.c_void_p
            L.shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_uint64]
            L.shm_ring_open.restype = ctypes.c_void_p
            L.shm_ring_open.argtypes = [ctypes.c_char_p]
            L.shm_ring_slot_bytes.restype = ctypes.c_uint64
            L.shm_ring_slot_bytes.argtypes = [ctypes.c_void_p]
            L.shm_ring_push.restype = ctypes.c_int
            L.shm_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_int]
            L.shm_ring_pop.restype = ctypes.c_int64
            L.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_uint64, ctypes.c_int]
            L.shm_ring_close.argtypes = [ctypes.c_void_p]
            L.shm_ring_disown.argtypes = [ctypes.c_void_p]
            _lib = L
    return _lib


class TCPStore:
    """ref: paddle.distributed's TCPStore (C++ master KV).

    ``is_master=True`` hosts the server in-process; every instance is also a
    client. ``get`` blocks until the key is set (rendezvous semantics).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: int = 900):
        L = lib()
        self._L = L
        self._server = None
        if is_master:
            self._server = L.tcp_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: failed to bind port {port}")
            port = L.tcp_store_server_port(self._server)
        self.host = host
        self.port = port
        self._client = L.tcp_store_client_connect(host.encode(), port)
        if not self._client:
            if self._server:
                L.tcp_store_server_stop(self._server)
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._L.tcp_store_set(self._client, key.encode(), data,
                                 len(data)) != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n = self._L.tcp_store_get(self._client, key.encode(), buf, cap)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        if n > cap:  # value larger than the probe buffer: refetch full size
            buf = ctypes.create_string_buffer(n)
            n2 = self._L.tcp_store_get(self._client, key.encode(), buf, n)
            if n2 != n:
                raise RuntimeError("TCPStore.get failed on refetch")
        return buf.raw[:n]

    def add(self, key: str, amount: int = 1) -> int:
        r = self._L.tcp_store_add(self._client, key.encode(), amount)
        if r == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return int(r)

    def check(self, key: str) -> bool:
        r = self._L.tcp_store_check(self._client, key.encode())
        if r < 0:
            raise RuntimeError("TCPStore.check failed")
        return bool(r)

    def delete_key(self, key: str) -> bool:
        return bool(self._L.tcp_store_delete(self._client, key.encode()))

    def wait(self, keys) -> None:
        for k in ([keys] if isinstance(keys, str) else keys):
            self.get(k)

    def barrier(self, name: str, world_size: int) -> None:
        """All participants call this; returns once all arrived."""
        import time
        n = self.add(f"__barrier/{name}", 1)
        if n == world_size:
            self.set(f"__barrier/{name}/done", b"1")
        else:
            self.get(f"__barrier/{name}/done")

    def close(self):
        if self._client:
            self._L.tcp_store_client_close(self._client)
            self._client = None
        if self._server:
            self._L.tcp_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmRing:
    """SPSC shared-memory ring (the DataLoader worker->parent transport)."""

    def __init__(self, name: str, slots: int = 8,
                 slot_bytes: int = 16 << 20, create: bool = True):
        L = lib()
        self._L = L
        self.name = name
        if create:
            self._h = L.shm_ring_create(name.encode(), slots, slot_bytes)
        else:
            self._h = L.shm_ring_open(name.encode())
        if not self._h:
            raise RuntimeError(f"ShmRing: cannot "
                               f"{'create' if create else 'open'} {name!r}")
        self.slot_bytes = int(L.shm_ring_slot_bytes(self._h))

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        r = self._L.shm_ring_push(self._h, data, len(data), timeout_ms)
        if r == -2:
            raise ValueError(
                f"ShmRing: payload {len(data)}B exceeds slot capacity "
                f"{self.slot_bytes}B — raise use_shared_memory slot size")
        return r == 0

    def pop(self, timeout_ms: int = -1) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(self.slot_bytes)
        n = self._L.shm_ring_pop(self._h, buf, self.slot_bytes, timeout_ms)
        if n < 0:
            return None
        return buf.raw[:n]

    def disown(self):
        """Mark this handle non-owner (a forked child must not destroy the
        semaphores / unlink shm the parent is still using)."""
        if self._h:
            self._L.shm_ring_disown(self._h)

    def close(self):
        if self._h:
            self._L.shm_ring_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
