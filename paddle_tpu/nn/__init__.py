"""paddle.nn namespace (parity: python/paddle/nn/__init__.py in the reference)."""

from . import functional, initializer
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from .layer import Layer, ParamAttr
from .layers.activation import (CELU, ELU, GELU, GLU, SELU, Hardshrink, Hardsigmoid,
                                Hardswish, Hardtanh, LeakyReLU, LogSigmoid,
                                LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, RReLU,
                                Sigmoid, SiLU, Softmax, Softplus, Softshrink,
                                Softsign, Swish, Tanh, Tanhshrink, ThresholdedReLU)
from .layers.common import (AlphaDropout, Bilinear, ChannelShuffle, CosineSimilarity,
                            Dropout, Dropout2D, Dropout3D, Embedding, Flatten,
                            Identity, Linear, Pad1D, Pad2D, Pad3D, PixelShuffle,
                            PixelUnshuffle, Unfold, Upsample, UpsamplingBilinear2D,
                            UpsamplingNearest2D, ZeroPad2D)
from .layers.container import LayerDict, LayerList, ParameterList, Sequential
from .layers.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
                          Conv3DTranspose)
from .layers.loss import (BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CTCLoss,
                          CrossEntropyLoss, HingeEmbeddingLoss, KLDivLoss, L1Loss,
                          MarginRankingLoss, MSELoss, NLLLoss, SmoothL1Loss,
                          TripletMarginLoss)
from .layers.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                          GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                          LayerNorm, LocalResponseNorm, RMSNorm, SpectralNorm,
                          SyncBatchNorm)
from .layers.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                             AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
                             AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                             MaxPool3D)
from .layers.rnn import (GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell, SimpleRNN,
                         SimpleRNNCell)
from .layers.transformer import (MultiHeadAttention, Transformer, TransformerDecoder,
                                 TransformerDecoderLayer, TransformerEncoder,
                                 TransformerEncoderLayer)
from .layout import ChannelsLast, to_channels_first, to_channels_last

# paddle.nn.utils
from . import utils  # noqa: E402

from . import quant  # noqa: E402
