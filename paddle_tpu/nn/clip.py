"""Gradient clipping (parity: python/paddle/nn/clip.py — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm, applied by the optimizer before the update)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, _wrap_value

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _wrap_value(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, _wrap_value((g._value * scale).astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = 0.0
        clipped_any = False
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            clipped_any = True
            sq = sq + jnp.sum(jnp.square(g._value.astype(jnp.float32)))
        if not clipped_any:
            return params_grads
        gnorm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _wrap_value((g._value * scale).astype(g._value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility paddle also ships (paddle.nn.utils.clip_grad_norm_)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return None
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = (p.grad._value * scale).astype(p.grad._value.dtype)
    return _wrap_value(total)
