"""paddle.nn.functional namespace (parity: python/paddle/nn/functional/__init__.py)."""

from .activation import (celu, elu, gelu, glu, gumbel_softmax, hardshrink,
                         hardsigmoid, hardswish, hardtanh, leaky_relu, log_sigmoid,
                         log_softmax, logsigmoid, maxout, mish, prelu, relu, relu6,
                         rrelu, selu, sigmoid, silu, softmax, softplus, softshrink,
                         softsign, stanh, swish, tanh, tanhshrink, thresholded_relu)
from .attention import (attention_probs, flash_attention,
                        scaled_dot_product_attention, sequence_mask)
from .common import (alpha_dropout, bicubic_interp, bilinear_interp,
                     channel_shuffle, cosine_similarity, dropout,
                     pairwise_distance, softmax2d,
                     dropout2d, dropout3d, embedding, interpolate, label_smooth,
                     linear, linear_interp, nearest_interp, normalize, one_hot,
                     pad, pad2d, pad3d, pixel_shuffle, pixel_unshuffle,
                     sparse_attention, trilinear_interp,
                     unfold, upsample, zeropad2d)
from .conv import (conv1d, conv1d_transpose, conv2d, conv2d_fusion,
                   conv2d_transpose, conv3d,
                   conv3d_transpose, conv_transpose1d, conv_transpose2d,
                   conv_transpose3d, depthwise_conv2d,
                   depthwise_conv2d_transpose)
from .loss import (adaptive_log_softmax_with_loss, binary_cross_entropy,
                   binary_cross_entropy_with_logits, bpr_loss, center_loss,
                   class_center_sample, cos_sim, cosine_embedding_loss,
                   cross_entropy, ctc_loss, dice_loss, gaussian_nll_loss,
                   hinge_embedding_loss, hsigmoid_loss, huber_loss,
                   identity_loss, kl_div, l1_loss, log_loss,
                   margin_cross_entropy, margin_ranking_loss,
                   modified_huber_loss, mse_loss, multi_label_soft_margin_loss,
                   multi_margin_loss, nll_loss, npair_loss, poisson_nll_loss,
                   rank_loss, rnnt_loss, sigmoid_focal_loss, smooth_l1_loss,
                   soft_margin_loss, softmax_with_cross_entropy,
                   square_error_cost, squared_l2_distance, squared_l2_norm,
                   teacher_student_sigmoid_loss, triplet_margin_loss,
                   triplet_margin_with_distance_loss)
from .norm import (batch_norm, group_norm, instance_norm, layer_norm,
                   local_response_norm, rms_norm, spectral_norm,
                   sync_batch_norm)
from .vision import (affine_grid, bilinear, feature_alpha_dropout, fold,
                     grid_sample, temporal_shift)
from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
                      adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
                      avg_pool1d, avg_pool2d, avg_pool3d, fractional_max_pool2d,
                      fractional_max_pool3d, lp_pool1d, lp_pool2d, max_pool1d,
                      max_pool2d, max_pool3d, max_unpool1d, max_unpool2d,
                      max_unpool3d, max_pool2d_with_index,
                      max_pool3d_with_index, pool2d, pool3d, spp, unpool,
                      unpool3d)
from .fused_rnn import fusion_gru, fusion_lstm, gru_unit, lstm_unit, multi_gru

# Register the functional surface in the op schema registry: upstream these
# ARE ops.yaml kernels (conv2d, softmax, cross_entropy, ... all dispatch to
# phi kernels), so the single source of truth must list them (docs/OPS.md).
def _register_functional():
    import types as _t

    from ...core.dispatch import OP_REGISTRY, register_op
    for _k, _v in list(globals().items()):
        if _k.startswith("_") or isinstance(_v, (_t.ModuleType, type)):
            continue
        if not callable(_v) or _k in OP_REGISTRY:
            continue
        register_op(_k, _v, doc=(_v.__doc__ or "").strip().split("\n")[0],
                    public=_v)


_register_functional()
