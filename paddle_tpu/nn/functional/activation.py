"""Activation functionals.

Parity target: ``python/paddle/nn/functional/activation.py`` in the reference.
All map to jax.nn / jnp primitives; XLA fuses them into adjacent matmuls on TPU.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op, unary_factory

relu = unary_factory("relu", jax.nn.relu)
relu6 = unary_factory("relu6", jax.nn.relu6)
sigmoid = unary_factory("sigmoid", jax.nn.sigmoid)
tanh = unary_factory("tanh", jnp.tanh)
silu = unary_factory("silu", jax.nn.silu)
swish = silu
mish = unary_factory("mish", jax.nn.mish)
softsign = unary_factory("softsign", jax.nn.soft_sign)
tanhshrink = unary_factory("tanhshrink", lambda x: x - jnp.tanh(x))
hardswish = unary_factory("hardswish", jax.nn.hard_swish)
hardsigmoid = unary_factory("hardsigmoid",
                            lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))


def gelu(x, approximate=False, name=None):
    x = ensure_tensor(x)
    return forward_op("gelu", lambda v: jax.nn.gelu(v, approximate=bool(approximate)),
                      [x])


def leaky_relu(x, negative_slope=0.01, name=None):
    return forward_op("leaky_relu",
                      lambda v: jax.nn.leaky_relu(v, negative_slope),
                      [ensure_tensor(x)])


def elu(x, alpha=1.0, name=None):
    return forward_op("elu", lambda v: jax.nn.elu(v, alpha), [ensure_tensor(x)])


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return forward_op("selu",
                      lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                      [ensure_tensor(x)])


def celu(x, alpha=1.0, name=None):
    return forward_op("celu", lambda v: jax.nn.celu(v, alpha), [ensure_tensor(x)])


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return forward_op("hardtanh", lambda v: jnp.clip(v, min, max), [ensure_tensor(x)])


def hardshrink(x, threshold=0.5, name=None):
    return forward_op("hardshrink",
                      lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0),
                      [ensure_tensor(x)])


def softshrink(x, threshold=0.5, name=None):
    return forward_op(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)),
        [ensure_tensor(x)])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return forward_op(
        "softplus",
        lambda v: jnp.where(v * beta > threshold, v,
                            jnp.log1p(jnp.exp(beta * v)) / beta),
        [ensure_tensor(x)])


def logsigmoid(x, name=None):
    return forward_op("logsigmoid", jax.nn.log_sigmoid, [ensure_tensor(x)])


log_sigmoid = logsigmoid


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import canonical_dtype
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)

    def impl(v):
        if dt is not None:
            v = v.astype(dt)
        return jax.nn.softmax(v, axis=int(axis))

    return forward_op("softmax", impl, [x])


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import canonical_dtype
    x = ensure_tensor(x)
    dt = canonical_dtype(dtype)

    def impl(v):
        if dt is not None:
            v = v.astype(dt)
        return jax.nn.log_softmax(v, axis=int(axis))

    return forward_op("log_softmax", impl, [x])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...ops.random import _next_key
    x = ensure_tensor(x)
    key = _next_key()

    def impl(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=int(axis))
        if hard:  # straight-through: hard value, soft gradient
            idx = jnp.argmax(y, axis=int(axis), keepdims=True)
            y_hard = jnp.put_along_axis(jnp.zeros_like(y), idx,
                                        jnp.ones(idx.shape, y.dtype), int(axis),
                                        inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return forward_op("gumbel_softmax", impl, [x])


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def impl(v, w):
        if w.size > 1:
            ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v >= 0, v, w * v)

    return forward_op("prelu", impl, [x, weight])


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    from ...ops.random import _next_key
    x = ensure_tensor(x)
    if training:
        key = _next_key()
        return forward_op(
            "rrelu",
            lambda v: jnp.where(v >= 0, v, v * jax.random.uniform(
                key, v.shape, v.dtype, lower, upper)),
            [x])
    mid = (lower + upper) / 2.0
    return forward_op("rrelu", lambda v: jnp.where(v >= 0, v, v * mid), [x])


def glu(x, axis=-1, name=None):
    return forward_op("glu", lambda v: jax.nn.glu(v, axis=int(axis)),
                      [ensure_tensor(x)])


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def impl(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return forward_op("maxout", impl, [x])


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return forward_op("stanh", lambda v: scale_b * jnp.tanh(scale_a * v),
                      [ensure_tensor(x)])


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return forward_op("thresholded_relu",
                      lambda v: jnp.where(v > threshold, v, value),
                      [ensure_tensor(x)])
