"""Attention functionals.

Parity target: ``paddle.nn.functional.scaled_dot_product_attention`` (reference:
``python/paddle/nn/functional/flash_attention.py``, backed by
``paddle/phi/kernels/gpu/flash_attn_kernel.cu`` wrapping third_party/flashattn).
TPU redesign: on TPU the Pallas flash-attention kernel (kernels/flash_attention.py) is
used when available; the jnp path below is the reference implementation and the CPU
fallback. Layout is paddle's [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None,
              dropout_key=None):
    """Pure-jax reference attention on [B, S, H, D]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B,H,S,D] layout for the matmuls
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        if jnp.issubdtype(mask.dtype, jnp.bool_):
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Flash attention entry (paddle layout [B, S, H, D]).

    Uses the Pallas TPU kernel when shapes/backend allow, else the jnp reference.
    """
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    args = [query, key, value]
    if attn_mask is not None:
        args.append(ensure_tensor(attn_mask))

    dk = None
    if dropout_p > 0.0 and training:
        from ...ops.random import _next_key
        dk = _next_key()

    use_pallas = _pallas_ok(query, attn_mask, dropout_p if training else 0.0)

    def impl(q, k, v, *m):
        if use_pallas:
            from ...kernels.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=is_causal)
        return _sdpa_ref(q, k, v, m[0] if m else None,
                         dropout_p if training else 0.0, is_causal, dropout_key=dk)

    return forward_op("scaled_dot_product_attention", impl, args)


def _pallas_ok(q, mask, dropout_p) -> bool:
    if mask is not None or dropout_p > 0.0:
        return False
    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform not in ("tpu", "axon"):
            return False
        from ...kernels import flash_attention  # noqa: F401 — kernel available?
    except Exception:
        return False
    d = q.shape[-1]
    sq = q.shape[1]
    return d % 128 == 0 and sq % 128 == 0


def attention_probs(query, key, attn_mask=None, scale=None):
    """Materialized softmax attention weights [B, H, Sq, Sk] (need_weights path)."""
    query, key = ensure_tensor(query), ensure_tensor(key)
    args = [query, key]
    if attn_mask is not None:
        args.append(ensure_tensor(attn_mask))

    def impl(q, k, *m):
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
        if m:
            mask = m[0]
            if jnp.issubdtype(mask.dtype, jnp.bool_):
                logits = jnp.where(mask, logits, -jnp.inf)
            else:
                logits = logits + mask
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)

    return forward_op("attention_probs", impl, args)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None, fixed_seed_offset=None,
                    rng_name="", training=True):
    """paddle.nn.functional.flash_attention.flash_attention parity: returns
    (out, softmax); softmax is None unless return_softmax (flash never materializes
    the probability matrix — same contract as the reference kernel)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal,
                                       training)
    return out, None


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ml = int(maxlen) if maxlen is not None else int(x.numpy().max())
    from ...core.dtype import canonical_dtype
    dt = canonical_dtype(dtype)

    def impl(v):
        return (jnp.arange(ml) < v[..., None]).astype(dt)

    return forward_op("sequence_mask", impl, [x], differentiable=False)
