"""Common functionals: linear, dropout, padding, embedding, interpolation, similarity.

Parity target: ``python/paddle/nn/functional/common.py`` + ``input.py`` in the
reference. Dropout draws from the global splittable RNG (TPU-native replacement for
Paddle's per-device generator + RNGStatesTracker; distributed variants fold in mesh
axes — see distributed/random.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op
from ...ops.random import _next_key


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle weight layout [in, out] (ref: nn.functional.linear)."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if bias is not None:
        return forward_op("linear", lambda v, w, b: v @ w + b,
                          [x, weight, ensure_tensor(bias)])
    return forward_op("linear", lambda v, w: v @ w, [x, weight])


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return forward_op("dropout_scale", lambda v: v * (1.0 - p), [x])
        return x
    if isinstance(p, Tensor):
        p = float(p.item())
    key = _next_key()
    ax = (axis,) if isinstance(axis, int) else axis

    def impl(v):
        shape = v.shape if ax is None else tuple(
            v.shape[i] if i in ax else 1 for i in range(v.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return forward_op("dropout", impl, [x])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=list(ax), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=list(ax), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    key = _next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def impl(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((1 - p) * (1 + p * alpha_p ** 2)))
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return forward_op("alpha_dropout", impl, [x])


_PAD_MODE = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):  # noqa: A002
    """paddle.nn.functional.pad: `pad` is per-dim [lo, hi] pairs; for 4-D/5-D inputs
    with data_format, `pad` covers only the spatial dims (paddle semantics)."""
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad._value).reshape(-1)]
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # spatial-only padding per data_format; paddle orders pad back-to-front
        if data_format is None:
            data_format = {3: "NCL", 4: "NCHW", 5: "NCDHW"}[nd]
        n_spatial = len(pad) // 2
        spatial_pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        pairs = [(0, 0)] * nd
        if data_format.startswith("NC"):
            for i, pr in enumerate(spatial_pairs):
                pairs[2 + i] = pr
        else:  # channels-last
            for i, pr in enumerate(spatial_pairs):
                pairs[1 + i] = pr

    jmode = _PAD_MODE[mode]

    def impl(v):
        if jmode == "constant":
            return jnp.pad(v, pairs, mode="constant", constant_values=value)
        return jnp.pad(v, pairs, mode=jmode)

    return forward_op("pad", impl, [x])


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Gather rows of `weight` (ref: nn.functional.embedding). `sparse` accepted for
    API parity; XLA gathers are already efficient, there is no SelectedRows path."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def impl(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return forward_op("embedding", impl, [x, weight])


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh
    return _oh(x, num_classes)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def impl(a, b):
        num = jnp.sum(a * b, axis=int(axis))
        den = jnp.linalg.norm(a, axis=int(axis)) * jnp.linalg.norm(b, axis=int(axis))
        return num / jnp.maximum(den, eps)

    return forward_op("cosine_similarity", impl, [x1, x2])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def impl(v):
        n = jnp.sum(jnp.abs(v) ** p, axis=int(axis), keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)

    return forward_op("normalize", impl, [x])


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    """Image resize (ref: nn.functional.interpolate → phi interpolate kernels);
    lowered to jax.image.resize."""
    x = ensure_tensor(x)
    nd = x.ndim
    channels_last = data_format in ("NHWC", "NDHWC", "NLC")
    spatial_idx = list(range(1, nd - 1)) if channels_last else list(range(2, nd))

    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in np.asarray(size._value).reshape(-1)]
        out_spatial = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in
                       (size if isinstance(size, (list, tuple)) else [size])]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial_idx)
        out_spatial = [int(x.shape[i] * s) for i, s in zip(spatial_idx, scale_factor)]

    out_shape = list(x.shape)
    for i, s in zip(spatial_idx, out_spatial):
        out_shape[i] = s

    jmode = {"nearest": "nearest", "bilinear": "bilinear", "linear": "linear",
             "trilinear": "trilinear", "bicubic": "cubic", "area": "linear"}[mode]

    if align_corners and mode in ("linear", "bilinear", "trilinear"):
        # paddle align_corners grid: src = dst * (in-1)/(out-1); separable 1-D lerp
        def impl(v):
            out = v
            for ax, osz in zip(spatial_idx, out_spatial):
                isz = out.shape[ax]
                if osz == isz:
                    continue
                pos = jnp.linspace(0.0, isz - 1, osz) if osz > 1 else jnp.zeros((1,))
                i0 = jnp.floor(pos).astype(jnp.int32)
                i1 = jnp.minimum(i0 + 1, isz - 1)
                w = (pos - i0).astype(v.dtype)
                wshape = [1] * out.ndim
                wshape[ax] = osz
                w = w.reshape(wshape)
                lo = jnp.take(out, i0, axis=ax)
                hi = jnp.take(out, i1, axis=ax)
                out = lo * (1 - w) + hi * w
            return out.astype(v.dtype)

        return forward_op("interpolate_ac", impl, [x])

    def impl(v):
        return jax.image.resize(v, tuple(out_shape), method=jmode).astype(v.dtype)

    return forward_op("interpolate", impl, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = int(upscale_factor)

    def impl(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return forward_op("pixel_shuffle", impl, [x])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = int(downscale_factor)

    def impl(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        raise NotImplementedError

    return forward_op("pixel_unshuffle", impl, [x])


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def impl(v):
        n, c, h, w = v.shape
        v = v.reshape(n, groups, c // groups, h, w)
        return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return forward_op("channel_shuffle", impl, [x])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref: nn.functional.unfold)."""
    x = ensure_tensor(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    k, s, p, d = _pair(kernel_sizes), _pair(strides), _pair(paddings), _pair(dilations)

    def impl(v):
        n, c, h, w = v.shape
        patches = jax.lax.conv_general_dilated_patches(
            v, filter_shape=k, window_strides=s,
            padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n2, ckk, oh, ow = patches.shape
        return patches.reshape(n2, ckk, oh * ow)

    return forward_op("unfold", impl, [x])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)

    def impl(v):
        k = v.shape[-1]
        if prior_dist is None:
            return (1 - epsilon) * v + epsilon / k
        return (1 - epsilon) * v + epsilon * prior_dist._value

    return forward_op("label_smooth", impl, [label])


def pairwise_distance(x, y, p: float = 2.0, epsilon: float = 1e-6,
                      keepdim: bool = False, name=None):
    """p-norm distance between corresponding rows (ref:
    nn.functional.pairwise_distance / nn.PairwiseDistance)."""
    x, y = ensure_tensor(x), ensure_tensor(y)

    def impl(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return forward_op("pairwise_distance", impl, [x, y])


def softmax2d(x, name=None):
    """Channel-wise softmax over NCHW inputs (ref: nn.Softmax2D)."""
    x = ensure_tensor(x)
    if x.ndim not in (3, 4):
        raise ValueError(f"softmax2d expects CHW or NCHW input, got rank "
                         f"{x.ndim}")
    import jax.nn as _jnn
    return forward_op("softmax2d",
                      lambda v: _jnn.softmax(v, axis=-3), [x])


# r5: interp-mode singles (upstream each mode is its own registered kernel:
# linear_interp/bilinear_interp/nearest_interp/bicubic_interp/
# trilinear_interp — all route to the one XLA resize here), pad2d/pad3d
# legacy names, sparse_attention public name.
def linear_interp(x, size=None, scale_factor=None, align_corners=False,
                  data_format="NCW", name=None):
    """1-D linear resize (ref: linear_interp_v2 kernel)."""
    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode="linear", align_corners=align_corners,
                       data_format=data_format)


def bilinear_interp(x, size=None, scale_factor=None, align_corners=False,
                    data_format="NCHW", name=None):
    """2-D bilinear resize (ref: bilinear_interp_v2 kernel)."""
    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode="bilinear", align_corners=align_corners,
                       data_format=data_format)


def nearest_interp(x, size=None, scale_factor=None, align_corners=False,
                   data_format="NCHW", name=None):
    """Nearest-neighbor resize (ref: nearest_interp_v2 kernel)."""
    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode="nearest", align_corners=align_corners,
                       data_format=data_format)


def bicubic_interp(x, size=None, scale_factor=None, align_corners=False,
                   data_format="NCHW", name=None):
    """Bicubic resize (ref: bicubic_interp_v2 kernel)."""
    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode="bicubic", align_corners=align_corners,
                       data_format=data_format)


def trilinear_interp(x, size=None, scale_factor=None, align_corners=False,
                     data_format="NCDHW", name=None):
    """3-D trilinear resize (ref: trilinear_interp_v2 kernel)."""
    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode="trilinear", align_corners=align_corners,
                       data_format=data_format)


def pad2d(x, padding, mode="constant", value=0.0, data_format="NCHW",
          name=None):
    """Legacy 4-D pad (ref: pad2d_op) — routes to the general pad."""
    return pad(x, padding, mode=mode, value=value, data_format=data_format)


def pad3d(x, padding, mode="constant", value=0.0, data_format="NCDHW",
          name=None):
    """Legacy 5-D pad (ref: pad3d_op)."""
    return pad(x, padding, mode=mode, value=value, data_format=data_format)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block/CSR-masked attention under the reference's public name (ref:
    paddle.nn.functional.sparse_attention) — routes to the sparse
    package's masked-SDPA formulation (dense MXU tiles; see
    sparse.nn.functional.attention for the design argument)."""
    from ... import sparse as _sp
    from ...ops._helpers import ensure_tensor as _et
    q = _et(query)
    S = int(q.shape[2])
    csr = _sp.sparse_csr_tensor(sparse_csr_offset, sparse_csr_columns,
                                __import__("numpy").ones(
                                    int(_et(sparse_csr_columns).shape[-1]),
                                    dtype="float32"),
                                shape=[S, S])
    return _sp.nn.functional.attention(query, key, value, csr,
                                       key_padding_mask=key_padding_mask,
                                       attn_mask=attn_mask)
