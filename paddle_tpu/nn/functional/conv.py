"""Convolution functionals.

Parity target: ``python/paddle/nn/functional/conv.py`` (backed there by cuDNN phi
kernels). TPU redesign: a single ``jax.lax.conv_general_dilated`` entry per rank —
XLA lowers convs onto the MXU directly, so there is no algo-search/cudnn-autotune tier.
Paddle's default NCHW layout is preserved at the API; XLA repacks layouts internally.
"""

from __future__ import annotations

from typing import Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    t = tuple(int(x) for x in v)
    if len(t) == 1:
        return t * n
    return t


def _padding(padding, n, strides=None, in_spatial=None, k=None, dilation=None):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(int(x) for x in p) for p in padding]
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:  # [before0, after0, before1, after1,...]
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _dnums(nd, channels_last):
    if nd == 3:
        return ("NLC", "LIO" if channels_last else "OIL", "NLC") if channels_last \
            else ("NCL", "OIL", "NCL")
    if nd == 4:
        return ("NHWC", "HWIO", "NHWC") if channels_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channels_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(rank: int):
    def conv(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
             data_format=None, name=None):
        x, weight = ensure_tensor(x), ensure_tensor(weight)
        nd = rank + 2
        channels_last = (data_format or "NC...").startswith("N") and \
            (data_format in ("NLC", "NHWC", "NDHWC"))
        s = _tuple(stride, rank)
        d = _tuple(dilation, rank)
        pad = _padding(padding, rank)
        dn = _dnums(nd, channels_last)

        args = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])

        def impl(v, w, *b):
            # weight layout is paddle's [out_c, in_c/groups, *k]; transpose for
            # channels-last dimension numbers
            if channels_last:
                perm = tuple(range(2, nd)) + (1, 0)
                w = jnp.transpose(w, perm)
            out = jax.lax.conv_general_dilated(
                v, w, window_strides=s, padding=pad, rhs_dilation=d,
                dimension_numbers=dn, feature_group_count=groups,
                preferred_element_type=None)
            if b:
                bias_shape = [1] * nd
                bias_shape[nd - 1 if channels_last else 1] = b[0].shape[0]
                out = out + b[0].reshape(bias_shape)
            return out

        return forward_op(f"conv{rank}d", impl, args)

    conv.__name__ = f"conv{rank}d"
    return conv


conv1d = _conv(1)
conv2d = _conv(2)
conv3d = _conv(3)


def _conv_transpose(rank: int):
    def convt(x, weight, bias=None, stride=1, padding=0, output_padding=0,
              groups=1, dilation=1, data_format=None, output_size=None, name=None):
        x, weight = ensure_tensor(x), ensure_tensor(weight)
        nd = rank + 2
        channels_last = data_format in ("NLC", "NHWC", "NDHWC")
        s = _tuple(stride, rank)
        d = _tuple(dilation, rank)
        op = _tuple(output_padding, rank)
        pad = _padding(padding, rank)
        dn = _dnums(nd, channels_last)

        args = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])

        def impl(v, w, *b):
            # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
            if groups > 1:
                icg = w.shape[0] // groups
                w = w.reshape((groups, icg) + w.shape[1:])
                outs = []
                vs = jnp.split(v, groups, axis=nd - 1 if channels_last else 1)
                for g in range(groups):
                    outs.append(_one(vs[g], w[g]))
                return _fin(jnp.concatenate(outs, axis=nd - 1 if channels_last else 1), b)
            return _fin(_one(v, w), b)

        def _one(v, w):
            # grad-of-conv formulation: conv_transpose via lax.conv_transpose
            if channels_last:
                w2 = jnp.transpose(w, tuple(range(2, nd)) + (0, 1))  # spatial,I,O
            else:
                w2 = jnp.transpose(w, (1, 0) + tuple(range(2, nd)))  # OI spatial
            if isinstance(pad, str):
                padding_arg = pad
            else:
                padding_arg = [(d[i] * (w.shape[2 + i] - 1) - pad[i][0],
                                d[i] * (w.shape[2 + i] - 1) - pad[i][1] + op[i])
                               for i in range(rank)]
            out = jax.lax.conv_general_dilated(
                v, jnp.flip(w2, axis=tuple(range(2, nd)) if not channels_last
                            else tuple(range(rank))),
                window_strides=(1,) * rank, padding=padding_arg,
                lhs_dilation=s, rhs_dilation=d, dimension_numbers=dn)
            return out

        def _fin(out, b):
            if b:
                bias_shape = [1] * nd
                bias_shape[nd - 1 if channels_last else 1] = b[0].shape[0]
                out = out + b[0].reshape(bias_shape)
            return out

        return forward_op(f"conv{rank}d_transpose", impl, args)

    convt.__name__ = f"conv{rank}d_transpose"
    return convt


conv1d_transpose = _conv_transpose(1)
conv2d_transpose = _conv_transpose(2)
conv3d_transpose = _conv_transpose(3)


# torch-style aliases (the reference ecosystem accepts both spellings)
conv_transpose1d = conv1d_transpose
conv_transpose2d = conv2d_transpose
conv_transpose3d = conv3d_transpose


# ---------------------------------------------------------------------------
# r5: legacy conv op names (ref: depthwise_conv2d_op,
# depthwise_conv2d_transpose_op, conv2d_fusion_op). Upstream these are
# separate kernels for the groups==channels case and the fused
# conv+bias+act inference op; on TPU both lower to the same
# conv_general_dilated with feature_group_count — registered under their
# own names because their ops.yaml entries are distinct.
# ---------------------------------------------------------------------------

def depthwise_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                     data_format="NCHW", name=None):
    """Depthwise conv2d (groups == in_channels)."""
    w = weight
    groups = int(ensure_tensor(x).shape[1 if data_format == "NCHW" else -1])
    return conv2d(x, w, bias=bias, stride=stride, padding=padding,
                  dilation=dilation, groups=groups, data_format=data_format)


def depthwise_conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                               output_padding=0, dilation=1,
                               data_format="NCHW", name=None):
    """Depthwise transposed conv2d."""
    groups = int(ensure_tensor(x).shape[1 if data_format == "NCHW" else -1])
    return conv2d_transpose(x, weight, bias=bias, stride=stride,
                            padding=padding, output_padding=output_padding,
                            dilation=dilation, groups=groups,
                            data_format=data_format)


def conv2d_fusion(x, weight, bias=None, residual=None, stride=1, padding=0,
                  dilation=1, groups=1, activation="relu",
                  data_format="NCHW", name=None):
    """conv + bias (+ residual) + activation in one call (ref:
    conv2d_fusion_op — the inference epilogue fusion; XLA performs the
    same fusion, this is the API contract)."""
    out = conv2d(x, weight, bias=bias, stride=stride, padding=padding,
                 dilation=dilation, groups=groups, data_format=data_format)
    if residual is not None:
        out = out + ensure_tensor(residual)
    from .activation import relu
    if activation == "relu":
        return relu(out)
    if activation in (None, "", "identity"):
        return out
    from . import activation as _act
    return getattr(_act, activation)(out)
