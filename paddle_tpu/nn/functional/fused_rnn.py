"""Fused RNN ops (ref: fusion_gru_op / fusion_lstm_op / multi_gru_op —
the reference's oneDNN/CUDA fused recurrences).

TPU redesign: the recurrence is a lax.scan whose step does ONE [B, 3H]
(GRU) / [B, 4H] (LSTM) matmul — XLA pipelines the scan body on the MXU,
which is the fusion the upstream megakernel hand-codes. Weight layouts
follow the reference (wx [D, 3H/4H], wh [H, 3H/4H], gate order
update/reset/cand for GRU and i/f/c/o for LSTM)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._helpers import ensure_tensor, forward_op

__all__ = ["fusion_gru", "fusion_lstm", "multi_gru"]


def fusion_gru(x, wx, wh, bias=None, h0=None, is_reverse: bool = False,
               origin_mode: bool = False, name=None):
    """One-layer GRU over [B, T, D] -> hidden sequence [B, T, H]."""
    xt = ensure_tensor(x)
    wxt = ensure_tensor(wx)
    wht = ensure_tensor(wh)
    args = [xt, wxt, wht]
    if bias is not None:
        args.append(ensure_tensor(bias))
    if h0 is not None:
        args.append(ensure_tensor(h0))

    def impl(xv, wxv, whv, *rest):
        bv = rest[0] if bias is not None else None
        h0v = rest[-1] if h0 is not None else None
        B, T, D = xv.shape
        H = whv.shape[0]
        xs = xv @ wxv                                        # [B, T, 3H]
        if bv is not None:
            xs = xs + bv
        if is_reverse:
            xs = xs[:, ::-1]
        init = h0v if h0v is not None else jnp.zeros((B, H), xv.dtype)

        def step(h, xg):
            hg = h @ whv                                     # [B, 3H]
            u = jax.nn.sigmoid(xg[:, :H] + hg[:, :H])
            r = jax.nn.sigmoid(xg[:, H:2 * H] + hg[:, H:2 * H])
            c = jnp.tanh(xg[:, 2 * H:] + r * hg[:, 2 * H:])
            if origin_mode:
                nh = u * h + (1 - u) * c
            else:
                nh = (1 - u) * h + u * c
            return nh, nh

        _, hs = lax.scan(step, init, xs.transpose(1, 0, 2))
        out = hs.transpose(1, 0, 2)
        return out[:, ::-1] if is_reverse else out

    return forward_op("fusion_gru", impl, args)


def fusion_lstm(x, wx, wh, bias=None, h0=None, c0=None,
                is_reverse: bool = False, name=None):
    """One-layer LSTM over [B, T, D] -> (hidden seq [B, T, H],
    cell seq [B, T, H])."""
    xt = ensure_tensor(x)
    wxt = ensure_tensor(wx)
    wht = ensure_tensor(wh)
    args = [xt, wxt, wht]
    if bias is not None:
        args.append(ensure_tensor(bias))
    if h0 is not None:
        args.append(ensure_tensor(h0))
        args.append(ensure_tensor(c0))

    def impl(xv, wxv, whv, *rest):
        bv = rest[0] if bias is not None else None
        B, T, D = xv.shape
        H = whv.shape[0]
        xs = xv @ wxv                                        # [B, T, 4H]
        if bv is not None:
            xs = xs + bv
        if is_reverse:
            xs = xs[:, ::-1]
        if h0 is not None:
            init = (rest[-2], rest[-1])
        else:
            init = (jnp.zeros((B, H), xv.dtype),
                    jnp.zeros((B, H), xv.dtype))

        def step(carry, xg):
            h, c = carry
            g = xg + h @ whv
            i = jax.nn.sigmoid(g[:, :H])
            f = jax.nn.sigmoid(g[:, H:2 * H])
            cc = jnp.tanh(g[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(g[:, 3 * H:])
            nc = f * c + i * cc
            nh = o * jnp.tanh(nc)
            return (nh, nc), (nh, nc)

        _, (hs, cs) = lax.scan(step, init, xs.transpose(1, 0, 2))
        out_h = hs.transpose(1, 0, 2)
        out_c = cs.transpose(1, 0, 2)
        if is_reverse:
            out_h, out_c = out_h[:, ::-1], out_c[:, ::-1]
        return out_h, out_c

    return forward_op("fusion_lstm", impl, args)


def multi_gru(x, wx_list, wh_list, bias_list=None, layers: int = None,
              name=None):
    """Stacked bidirectional GRU (ref: multi_gru_op): each layer runs a
    forward and a reverse fusion_gru and concatenates."""
    n = layers if layers is not None else len(wx_list) // 2
    out = x
    for l in range(n):
        fwd = fusion_gru(out, wx_list[2 * l], wh_list[2 * l],
                         bias_list[2 * l] if bias_list else None)
        bwd = fusion_gru(out, wx_list[2 * l + 1], wh_list[2 * l + 1],
                         bias_list[2 * l + 1] if bias_list else None,
                         is_reverse=True)
        from ...ops.manipulation import concat
        out = concat([fwd, bwd], axis=-1)
    return out


def _register():
    from ...core.dispatch import register_op
    for _n in __all__:
        _f = globals()[_n]
        register_op(_n, _f, (_f.__doc__ or "").strip().split("\n")[0],
                    category="fused", public=_f)


_register()


def gru_unit(input, hidden, weight, bias=None, activation="tanh",  # noqa: A002
             gate_activation="sigmoid", origin_mode: bool = False,
             name=None):
    """Single GRU cell step (ref: gru_unit_op): ``input [B, 3H]`` (already
    projected), ``hidden [B, H]``, ``weight [H, 3H]``. Returns the new
    hidden state."""
    it = ensure_tensor(input)
    ht = ensure_tensor(hidden)
    wt = ensure_tensor(weight)
    args = [it, ht, wt]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def impl(xg, h, w, *b):
        H = h.shape[1]
        if b:
            xg = xg + b[0]
        hg = h @ w
        u = jax.nn.sigmoid(xg[:, :H] + hg[:, :H])
        r = jax.nn.sigmoid(xg[:, H:2 * H] + hg[:, H:2 * H])
        c = jnp.tanh(xg[:, 2 * H:] + r * hg[:, 2 * H:])
        return u * h + (1 - u) * c if origin_mode else (1 - u) * h + u * c

    return forward_op("gru_unit", impl, args)


def lstm_unit(x, pre_cell, forget_bias: float = 0.0, name=None):
    """Single LSTM cell step over pre-projected gates (ref: lstm_unit_op):
    ``x [B, 4H]`` fused i/f/c/o gates, ``pre_cell [B, H]``. Returns
    ``(hidden, cell)``."""
    xt = ensure_tensor(x)
    ct = ensure_tensor(pre_cell)

    def impl(g, c):
        H = c.shape[1]
        i = jax.nn.sigmoid(g[:, :H])
        f = jax.nn.sigmoid(g[:, H:2 * H] + forget_bias)
        cc = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:])
        nc = f * c + i * cc
        return o * jnp.tanh(nc), nc

    return forward_op("lstm_unit", impl, [xt, ct])


__all__ += ["gru_unit", "lstm_unit"]
