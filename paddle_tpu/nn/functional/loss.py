"""Loss functionals.

Parity target: ``python/paddle/nn/functional/loss.py`` in the reference.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor, forward_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Softmax cross entropy (ref: nn.functional.cross_entropy →
    softmax_with_cross_entropy phi kernel)."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    args = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def impl(logits, lab, *w):
        ax = axis % logits.ndim
        logp = jax.nn.log_softmax(logits, axis=ax) if use_softmax else jnp.log(
            jnp.clip(logits, 1e-30, None))
        n_classes = logits.shape[ax]
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=ax)
            valid = None
        else:
            lab_idx = lab
            if lab_idx.ndim == logits.ndim:  # trailing 1 dim
                lab_idx = jnp.squeeze(lab_idx, axis=ax)
            lab_idx = lab_idx.astype(jnp.int32)
            valid = lab_idx != ignore_index
            safe = jnp.where(valid, lab_idx, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, ax), axis=ax).squeeze(ax)
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(logp, axis=ax)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            if w:
                loss = loss * jnp.take(w[0], safe)
            loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if valid is not None:
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                if w:
                    denom = jnp.maximum(jnp.sum(
                        jnp.where(valid, jnp.take(w[0], jnp.where(valid, lab_idx, 0)),
                                  0.0)), 1e-12)
                return jnp.sum(loss) / denom
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return forward_op("cross_entropy", impl, args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps the reduced axis
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    args = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])

    def impl(logp, lab, *w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        wt = jnp.take(w[0], safe) if w else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * wt, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return forward_op("nll_loss", impl, args)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return forward_op("mse_loss",
                      lambda a, b: _reduce(jnp.square(a - b), reduction),
                      [ensure_tensor(input), ensure_tensor(label)])


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return forward_op("l1_loss",
                      lambda a, b: _reduce(jnp.abs(a - b), reduction),
                      [ensure_tensor(input), ensure_tensor(label)])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def impl(a, b):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        # paddle multiplies by delta (huber normalization)
        return _reduce(loss * delta, reduction)

    return forward_op("smooth_l1_loss", impl,
                      [ensure_tensor(input), ensure_tensor(label)])


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    args = [ensure_tensor(input), ensure_tensor(label)] + \
        ([ensure_tensor(weight)] if weight is not None else [])

    def impl(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return forward_op("binary_cross_entropy", impl, args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if pos_weight is not None:
        args.append(ensure_tensor(pos_weight))

    def impl(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with optional pos_weight
        log_sig_pos = -jax.nn.softplus(-z)
        log_sig_neg = -z - jax.nn.softplus(-z)
        if pw is not None:
            loss = -(pw * y * log_sig_pos + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig_pos + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return forward_op("bce_with_logits", impl, args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def impl(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return forward_op("kl_div", impl, [ensure_tensor(input), ensure_tensor(label)])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    return forward_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        [ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return forward_op(
        "hinge_embedding_loss",
        lambda x, y: _reduce(jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)),
                             reduction),
        [ensure_tensor(input), ensure_tensor(label)])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return forward_op("cosine_embedding_loss", impl,
                      [ensure_tensor(input1), ensure_tensor(input2),
                       ensure_tensor(label)])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,  # noqa: A002
                        swap=False, reduction="mean", name=None):
    def impl(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return forward_op("triplet_margin_loss", impl,
                      [ensure_tensor(input), ensure_tensor(positive),
                       ensure_tensor(negative)])


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return forward_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        [ensure_tensor(input), ensure_tensor(label)])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = [ensure_tensor(logit), ensure_tensor(label)] + \
        ([ensure_tensor(normalizer)] if normalizer is not None else [])

    def impl(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    return forward_op("sigmoid_focal_loss", impl, args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss via the standard alpha-recursion in log space (lax.scan over time).

    Ref capability: paddle.nn.functional.ctc_loss (warpctc in the reference).
    Expects log_probs [T, B, C] (paddle layout) already log-softmaxed or logits.
    """
    log_probs = ensure_tensor(log_probs)
    labels = ensure_tensor(labels)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def impl(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        ext_len = 2 * S + 1
        # extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, ext_len), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        def get_probs(t_lp):  # [B, ext_len]
            return jnp.take_along_axis(t_lp, ext, axis=1)

        # init alpha at t=0
        alpha0 = jnp.full((B, ext_len), neg_inf)
        p0 = get_probs(lp[0])
        alpha0 = alpha0.at[:, 0].set(p0[:, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, p0[:, 1], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t_lp):
            p = get_probs(t_lp)
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            new = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2) + p
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], 0)  # [T, B, ext_len]

        # pick alpha at t = in_len-1, positions 2*lab_len-1 and 2*lab_len
        t_idx = jnp.clip(in_len - 1, 0, T - 1).astype(jnp.int32)
        batch = jnp.arange(B)
        final = alphas[t_idx, batch]  # [B, ext_len]
        e1 = jnp.take_along_axis(final, jnp.clip(2 * lab_len - 1, 0, ext_len - 1)
                                 [:, None].astype(jnp.int32), 1)[:, 0]
        e2 = jnp.take_along_axis(final, jnp.clip(2 * lab_len, 0, ext_len - 1)
                                 [:, None].astype(jnp.int32), 1)[:, 0]
        ll = jnp.logaddexp(e1, e2)
        loss = -ll
        if norm_by_times:
            loss = loss / in_len.astype(loss.dtype)
        if reduction == "mean":
            return jnp.mean(loss / lab_len.astype(loss.dtype))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return forward_op("ctc_loss", impl,
                      [log_probs, labels, input_lengths, label_lengths])


def square_error_cost(input, label):  # noqa: A002
    return forward_op("square_error_cost", lambda a, b: jnp.square(a - b),
                      [ensure_tensor(input), ensure_tensor(label)])


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    def impl(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return forward_op("dice_loss", impl, [ensure_tensor(input), ensure_tensor(label)])


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """ref: paddle.nn.functional.huber_loss (quadratic within delta)."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
        return _reduce(out, reduction)
    return forward_op("huber_loss", f, [x, y])


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    """ref: soft_margin_loss — log(1 + exp(-y * x)) with y in {-1, 1}."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        # softplus form: log1p(exp(z)) overflows for moderate margins
        return _reduce(jax.nn.softplus(-b * a), reduction)
    return forward_op("soft_margin_loss", f, [x, y])


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    """ref: multi-label one-vs-all BCE-with-logits averaged over classes."""
    x, y = ensure_tensor(input), ensure_tensor(label)
    w = None if weight is None else ensure_tensor(weight)

    def f(a, b, wv=None):
        per = -(b * jax.nn.log_sigmoid(a) + (1 - b) * jax.nn.log_sigmoid(-a))
        if wv is not None:
            per = per * wv
        return _reduce(per.mean(axis=-1), reduction)
    args = [x, y] if w is None else [x, y, w]
    return forward_op("multi_label_soft_margin_loss", f, args)


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean", name=None):
    """ref: poisson_nll_loss (Stirling term when full=True)."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def f(a, b):
        if log_input:
            out = jnp.exp(a) - b * a
        else:
            out = a - b * jnp.log(a + epsilon)
        if full:
            stirling = b * jnp.log(b + epsilon) - b + \
                0.5 * jnp.log(2 * jnp.pi * (b + epsilon))
            out = out + jnp.where(b > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return forward_op("poisson_nll_loss", f, [x, y])


def gaussian_nll_loss(input, label, variance, full=False,  # noqa: A002
                      epsilon=1e-6, reduction="mean", name=None):
    """ref: gaussian_nll_loss — 0.5*(log var + (x-y)^2/var) [+ const]."""
    x, y, v = ensure_tensor(input), ensure_tensor(label), \
        ensure_tensor(variance)

    def f(a, b, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + jnp.square(a - b) / var)
        if full:
            out = out + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(out, reduction)
    return forward_op("gaussian_nll_loss", f, [x, y, v])
